"""Locality scorer: route consumers to the node holding their input bytes.

A reduce task for partition ``j`` reads span ``j`` of EVERY map segment; a
chained map task reads exactly one bundle segment. Both placements reduce to
one question — which node holds the largest share of the bytes this task
will fetch? Score = Σ segment_bytes grouped by the segment's source node,
routed via soft ``NodeAffinitySchedulingStrategy`` (the controller's
``_candidate_nodes`` affinity ordering tries the pinned node first and falls
back to the normal hybrid order, so a busy/dead best node degrades to
default scheduling instead of stalling).

Source nodes resolve through ONE batched ``object_sources`` controller round
trip per exchange (the same directory lookup the span-fetch rung uses) with
the descriptor's recorded producer node as fallback — descriptors always
know where they were born even when the directory is momentarily behind.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ...core import api
from ...core.task_spec import NodeAffinitySchedulingStrategy

# Pin only when one node holds a DOMINANT share of the task's input bytes.
# Locality on a near-tie buys almost nothing (half the bytes cross the wire
# either way) but costs everything: argmax breaks every ~50/50 partition to
# the same marginally-larger node, piling the whole reduce stage onto it
# while its peers idle. Below the threshold the scheduler's own hybrid
# balance wins.
DOMINANT_SHARE = 0.65


def segment_nodes(descs: Sequence[Dict[str, Any]]) -> List[Optional[str]]:
    """Current source node of each descriptor's segment (best effort)."""
    out: List[Optional[str]] = [d.get("node") for d in descs]
    try:
        backend = api._global_runtime().backend
        sources_of = getattr(backend, "object_sources", None)
        if sources_of is None:
            return out
        resolved = sources_of([d["ref"].id.hex() for d in descs])
        for i, src in enumerate(resolved):
            if src and src.get("node"):
                out[i] = src["node"]
    except Exception:  # noqa: BLE001 — placement is advisory, never fatal
        pass
    return out


def best_node_for_partition(
    descs: Sequence[Dict[str, Any]],
    j: int,
    nodes: Sequence[Optional[str]],
) -> Optional[str]:
    """Node holding a dominant share of partition-``j`` bytes across the
    map segments; None on a near-tie (let the scheduler balance)."""
    score: Dict[str, int] = {}
    total = 0
    for d, node in zip(descs, nodes):
        try:
            nbytes = int(d["bytes"][j])
        except (KeyError, IndexError, TypeError, ValueError):
            continue
        if nbytes <= 0:
            continue
        total += nbytes
        if node is not None:
            score[node] = score.get(node, 0) + nbytes
    if not score or total <= 0:
        return None
    node, best = max(score.items(), key=lambda kv: kv[1])
    return node if best >= DOMINANT_SHARE * total else None


def best_node_for_bundles(bundles) -> Optional[str]:
    """Placement for a task consuming WHOLE bundles (train-side consumers):
    the node holding the largest share of the bundles' descriptor bytes."""
    descs = [b.desc for b in bundles if getattr(b, "desc", None) is not None]
    if not descs:
        return None
    nodes = segment_nodes(descs)
    score: Dict[str, int] = {}
    total = 0
    for d, node in zip(descs, nodes):
        nbytes = int(sum(d.get("bytes") or [0]))
        total += nbytes
        if node is not None:
            score[node] = score.get(node, 0) + nbytes
    if not score or total <= 0:
        return None
    node, best = max(score.items(), key=lambda kv: kv[1])
    return node if best >= DOMINANT_SHARE * total else None


def affinity_options(node: Optional[str]) -> Dict[str, Any]:
    """kwargs for ``RemoteFunction.options`` pinning softly to ``node``."""
    if node is None:
        return {}
    return {"scheduling_strategy":
            NodeAffinitySchedulingStrategy(node_id=node, soft=True)}
