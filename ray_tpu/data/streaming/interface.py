"""Streaming-plane contracts: the pull operator interface + run statistics.

The pull protocol (docs/STREAMING_DATA.md):

  * every physical operator exposes ``next_bundle() -> Optional[RefBundle]``;
    ``None`` means exhausted, permanently;
  * an operator REFILLS its bounded in-flight window only inside
    ``next_bundle`` — it pulls upstream exactly when it has window room, so
    backpressure needs no signaling at all: a slow consumer stops pulling,
    every window upstream fills to its bound, and the source stops reading.
    Blocks resident per operator (submitted but not yet handed downstream)
    never exceed the window — and `StreamStats` MEASURES that instead of
    trusting it (peak_resident, asserted in the perf smoke).

`StreamStats` is driver-side and lock-guarded (the ingest producer thread
and the training thread both touch it). Worker-side fetch-rung deltas ride
back in descriptors / task metadata (`transport.track_fetch`) and are merged
here, so ``fetch`` is a RUN-WIDE ledger: driver + every map/reduce task.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .. import transport


class StreamStats:
    """Per-run accounting for one PullExecutor execution."""

    def __init__(self):
        self._lock = threading.Lock()
        self.started = time.perf_counter()
        self.finished: Optional[float] = None
        # op index -> counters. "resident" = submitted - yielded: task
        # outputs this op currently holds (in flight or ready, not yet
        # pulled downstream). "wait_s" = time blocked resolving the head
        # task (upstream/compute starvation, the pull-side stall).
        self.ops: Dict[int, Dict[str, Any]] = {}
        self.fetch: Dict[str, int] = {}
        # Same ledger split by pipeline stage ("read"/"map"/"exchange_map"/
        # "exchange"): the bench isolates REDUCE-side traffic (group
        # "exchange") to assert cross-node bytes ≈ bytes consumed.
        self.fetch_groups: Dict[str, Dict[str, int]] = {}
        # Locality placement decisions (exchange reduces + affine maps):
        # node id -> tasks routed there; "none" = no affinity applied.
        self.placements: Dict[str, int] = {}
        # Output bundles handed to the consumer and not yet release()d —
        # visibility into consumer-held blocks (never blocks anything).
        self.delivered = {"resident": 0, "peak": 0, "total": 0}

    def op_entry(self, i: int, name: str, window: int) -> Dict[str, Any]:
        with self._lock:
            return self.ops.setdefault(i, {
                "name": name, "window": window, "submitted": 0, "yielded": 0,
                "rows": 0, "bytes": 0, "resident": 0, "peak_resident": 0,
                "wait_s": 0.0,
            })

    def on_submit(self, i: int) -> None:
        with self._lock:
            d = self.ops[i]
            d["submitted"] += 1
            d["resident"] += 1
            d["peak_resident"] = max(d["peak_resident"], d["resident"])

    def on_yield(self, i: int, rows: int, nbytes: int, wait_s: float) -> None:
        with self._lock:
            d = self.ops[i]
            d["yielded"] += 1
            d["rows"] += rows
            d["bytes"] += nbytes
            d["resident"] -= 1
            d["wait_s"] += wait_s

    def add_fetch(self, delta: Optional[Dict[str, int]],
                  group: Optional[str] = None) -> None:
        if not delta:
            return
        with self._lock:
            transport.merge_fetch_stats(self.fetch, delta)
            if group is not None:
                transport.merge_fetch_stats(
                    self.fetch_groups.setdefault(group, {}), delta)

    def on_placement(self, node: Optional[str]) -> None:
        with self._lock:
            key = node or "none"
            self.placements[key] = self.placements.get(key, 0) + 1

    def on_deliver(self) -> None:
        with self._lock:
            d = self.delivered
            d["total"] += 1
            d["resident"] += 1
            d["peak"] = max(d["peak"], d["resident"])

    def on_release(self) -> None:
        with self._lock:
            self.delivered["resident"] -= 1

    def done(self) -> None:
        self.finished = time.perf_counter()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "elapsed_s": (self.finished or time.perf_counter()) - self.started,
                "ops": {i: dict(d) for i, d in self.ops.items()},
                "fetch": dict(self.fetch),
                "fetch_groups": {g: dict(d)
                                 for g, d in self.fetch_groups.items()},
                "placements": dict(self.placements),
                "delivered": dict(self.delivered),
            }


class PhysicalOperator:
    """Base pull operator. Subclasses implement ``next_bundle``."""

    name = "op"

    def __init__(self, index: int, stats: StreamStats, window: int):
        self.index = index
        self.stats = stats
        self.window = max(1, int(window))
        self.lane = f"data/op{index}"
        stats.op_entry(index, self.name, self.window)

    def next_bundle(self):  # -> Optional[RefBundle]
        raise NotImplementedError

    def size_hint(self) -> Optional[int]:
        """Expected bundle count, when knowable BEFORE execution (read task
        count, materialized inputs). Lets an eager exchange fix its
        partition count without draining upstream first. None = unknown."""
        return None
