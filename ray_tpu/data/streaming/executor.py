"""PullExecutor — the bounded-window streaming execution plane.

Replaces the stage-barrier path of `data/executor.py` (kept there as
``execute_staged`` for A/B and as the zip/union fallback) with PULL-based
operators (reference: `data/_internal/execution/streaming_executor.py`, but
pull- instead of push-scheduled):

  * ONE-TO-ONE segments (read + fused map chains) run as
    `_WindowedTaskOp`s: at most ``window`` task outputs resident per op,
    refilled only when the downstream pulls — backpressure reaches the
    source with zero signaling (interface.py has the contract);
  * map/read outputs are arena-segment frames (`transport.put_bundle`):
    the task returns ONLY a span descriptor, chained consumers resolve it
    same-node zero-copy or via a `(name, offset, length)` bulk-span pull,
    and every resolution is rung-counted;
  * exchanges (`ExchangeOp`) keep the all-to-all barrier they inherently
    need (reduce j reads span j of EVERY map segment) but stream both
    edges: map tasks submit eagerly as upstream bundles arrive (when the
    partitioner needs no global statistics), reduce tasks yield through a
    window — and are PLACED on the node holding the largest share of their
    source bytes (locality.py, soft node affinity);
  * every op records flight spans on lane ``data/op{i}`` — ``data.wait``
    (head-of-line starvation while pulling), ``data.bundle`` (per-bundle
    yield, rows/bytes attrs), ``data.drain`` (exchange input barrier) — so
    `flight.ingest_report` can attribute where a pipeline stalls.

Run statistics (`StreamStats`) for the MOST RECENT execution in this
process are reachable via ``last_run_stats()`` — the bench and the perf
smoke assert rung traffic and bounded residency from there.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import cloudpickle

from ...core.api import get as ray_get, wait as ray_wait
from ...core.task_spec import SpreadSchedulingStrategy
from ...util import flight
from .. import transport
from ..context import DataContext
from ..plan import AllToAllOp, InputBlocksOp, LimitOp, LogicalPlan, ReadOp
from ..executor import (
    RefBundle,
    StreamingExecutor,
    _exec_chain,
    _exec_chain_segment,
    _exec_read_chain,
    _exec_read_chain_segment,
    _exchange_reduce,
    _exchange_reduce_segments,
    _partition_map,
    _partition_map_segment,
    _RandomPartition,
    _remote,
    _ShufflePost,
    read_payloads,
)
from . import locality
from .interface import PhysicalOperator, StreamStats

_LAST_STATS: Optional[StreamStats] = None


def last_run_stats() -> Optional[StreamStats]:
    """Stats of the most recent PullExecutor run in this process (the run
    may still be in progress — StreamStats is live and lock-guarded)."""
    return _LAST_STATS


def _limit_of(chain) -> Optional[int]:
    for op in chain or ():
        if isinstance(op, LimitOp):
            return op.n
    return None


class _WindowedTaskOp(PhysicalOperator):
    """Bounded-window submit/resolve engine.

    Subclasses supply tasks via ``_submit_one()`` (returns the pending
    entry, or None when the supply is exhausted). Entries are either
    ``("seg", desc_ref)`` — segment mode, the task's single return is the
    span descriptor (rows/bytes ride inside it) — or
    ``("pair", blocks_ref, meta_ref)`` — classic mode. Resolution blocks
    only on the HEAD entry and opportunistically batch-gets every other
    already-finished one in the same round trip (`_TaskStream`'s trick),
    so in-order yield costs one get per window refill, not per bundle.
    """

    def __init__(self, index: int, stats: StreamStats, window: int,
                 limit: Optional[int] = None):
        super().__init__(index, stats, window)
        self._pending: collections.deque = collections.deque()
        self._resolved: Dict[Any, Any] = {}
        self._rows_out = 0
        self._limit = limit
        self._done = False

    def _submit_one(self):
        raise NotImplementedError

    def _refill(self) -> None:
        while len(self._pending) < self.window:
            entry = self._submit_one()
            if entry is None:
                return
            self._pending.append(entry)
            self.stats.on_submit(self.index)

    def _resolve_batched(self, head_ref):
        """Value of ``head_ref``, batching in any other finished refs."""
        if head_ref not in self._resolved:
            pending = [e[-1] for e in self._pending
                       if e[0] != "ready" and e[-1] not in self._resolved]
            ready, _ = (ray_wait(pending, num_returns=len(pending), timeout=0)
                        if pending else ([], []))
            batch = [head_ref] + ready
            for ref, val in zip(batch, ray_get(batch)):
                self._resolved[ref] = val
        return self._resolved.pop(head_ref)

    def next_bundle(self) -> Optional[RefBundle]:
        if self._done:
            return None
        self._refill()
        if not self._pending:
            self._done = True
            return None
        t0 = time.monotonic_ns()
        entry = self._pending.popleft()
        if entry[0] == "seg":
            desc = self._resolve_batched(entry[1])
            self.stats.add_fetch(desc.pop("fetch", None), group=self.name)
            bundle = RefBundle(entry[1], int(desc["rows"][0]),
                               int(desc["bytes"][0]), desc=desc)
        else:
            meta = self._resolve_batched(entry[2])
            self.stats.add_fetch(meta.get("fetch"), group=self.name)
            bundle = RefBundle(entry[1], meta["num_rows"], meta["size_bytes"])
        t1 = time.monotonic_ns()
        wait_s = (t1 - t0) * 1e-9
        if wait_s > 1e-4:
            # The pull blocked: upstream/compute starvation — attributable.
            flight.record("data.wait", t0, t1, lane=self.lane)
        flight.record("data.bundle", t1, t1, lane=self.lane,
                      attrs={"rows": bundle.num_rows,
                             "bytes": bundle.size_bytes})
        self.stats.on_yield(self.index, bundle.num_rows, bundle.size_bytes,
                            wait_s)
        self._rows_out += bundle.num_rows
        if self._limit is not None and self._rows_out >= self._limit:
            self._done = True
            self._pending.clear()
            self._resolved.clear()
        return bundle


class InputOp(PhysicalOperator):
    """Pre-materialized bundles (InputBlocksOp): pure supply, no tasks."""

    name = "input"

    def __init__(self, index: int, stats: StreamStats, bundles):
        super().__init__(index, stats, window=max(1, len(bundles)))
        self._n = len(bundles)
        self._it = iter(bundles)

    def size_hint(self) -> Optional[int]:
        return self._n

    def next_bundle(self) -> Optional[RefBundle]:
        for b in self._it:
            self.stats.on_submit(self.index)
            self.stats.on_yield(self.index, b.num_rows, b.size_bytes, 0.0)
            return b
        return None


class ReadSourceOp(_WindowedTaskOp):
    """Source: read tasks with the first fused map chain baked in."""

    name = "read"

    def __init__(self, index, stats, window, ctx: DataContext,
                 src: ReadOp, chain):
        super().__init__(index, stats, window, limit=_limit_of(chain))
        payloads = list(read_payloads(ctx, src, chain))
        self._n = len(payloads)
        self._payloads = iter(payloads)
        self._segment = transport.transport_enabled()
        # Reads are the locality ROOT: every downstream placement chases the
        # node a read output landed on, so packed reads cascade the whole
        # pipeline onto one node. Spread them round-robin across the gang.
        spread = {"scheduling_strategy": SpreadSchedulingStrategy()}
        self._fn_seg = _remote(_exec_read_chain_segment).options(**spread)
        self._fn = _remote(_exec_read_chain, num_returns=2).options(**spread)

    def size_hint(self) -> Optional[int]:
        return self._n

    def _submit_one(self):
        for payload in self._payloads:
            if self._segment:
                return ("seg", self._fn_seg.remote(payload))
            blocks_ref, meta_ref = self._fn.remote(payload)
            return ("pair", blocks_ref, meta_ref)
        return None


class MapOp(_WindowedTaskOp):
    """Fused ONE-TO-ONE chain over an upstream operator. With locality
    placement on, each task softly pins to the node its input segment
    lives on — chained maps then stay with their data instead of
    re-pulling it across the wire."""

    name = "map"

    def __init__(self, index, stats, window, ctx: DataContext,
                 upstream: PhysicalOperator, chain):
        super().__init__(index, stats, window, limit=_limit_of(chain))
        self._upstream = upstream
        self._payload = cloudpickle.dumps(chain)
        self._segment = transport.transport_enabled()
        self._locality = ctx.locality_placement
        self._fn_seg = _remote(_exec_chain_segment)
        self._fn = _remote(_exec_chain, num_returns=2)

    def size_hint(self) -> Optional[int]:
        return self._upstream.size_hint()  # 1:1 over upstream bundles

    def _submit_one(self):
        b = self._upstream.next_bundle()
        if b is None:
            return None
        fn = self._fn_seg if self._segment else self._fn
        if self._locality and b.desc is not None and b.desc.get("node"):
            node = b.desc["node"]
            fn = fn.options(**locality.affinity_options(node))
            self.stats.on_placement(node)
        if self._segment:
            return ("seg", fn.remote(self._payload, b.blocks_ref))
        blocks_ref, meta_ref = fn.remote(self._payload, b.blocks_ref)
        return ("pair", blocks_ref, meta_ref)


class ExchangeOp(_WindowedTaskOp):
    """All-to-all over the pull plane. The reduce barrier is inherent
    (partition j spans every map segment), but both edges stream:

      * map tasks submit EAGERLY per arriving upstream bundle whenever the
        partitioner needs no global statistics (random_shuffle /
        shuffle-repartition with an explicit output count — the training
        ingest shape); other kinds drain first (`data.drain` span) because
        their partitioners derive from global row counts or samples;
      * reduce tasks yield through this op's window and are placed via the
        locality scorer — the descriptor values are already driver-side
        (they ARE the map results), so scoring adds one batched
        object_sources round trip, no extra data movement.
    """

    name = "exchange"

    def __init__(self, index, stats, window, ctx: DataContext,
                 op: AllToAllOp, upstream: PhysicalOperator,
                 staged: StreamingExecutor):
        super().__init__(index, stats, window)
        self._ctx = ctx
        self._op = op
        self._upstream = upstream
        self._staged = staged
        self._segment = transport.transport_enabled()
        self._started = False
        self._supply: Iterator[Callable[[], tuple]] = iter(())
        self._passthrough: collections.deque = collections.deque()

    def size_hint(self) -> Optional[int]:
        if self._op.num_outputs:
            return self._op.num_outputs
        if self._op.kind == "random_shuffle":
            return self._upstream.size_hint()  # shuffle keeps the count
        return None

    # -------------------------------------------------------------- start
    def _eager_spec(self):
        """(n, part_fn_factory, post_fn) when maps can submit before the
        input is drained — MUST mirror exchange_spec's construction. The
        partition count comes from num_outputs or the upstream's size hint
        (= what exchange_spec's len(bundles) would be), so results match
        the staged path bit for bit."""
        op = self._op
        shuffleish = (op.kind == "random_shuffle"
                      or (op.kind == "repartition" and op.shuffle))
        if not (self._segment and shuffleish):
            return None
        n = op.num_outputs or self._upstream.size_hint()
        if not n:
            return None
        seed = op.seed
        return (n,
                lambda i: _RandomPartition(n, None if seed is None else seed + i),
                _ShufflePost(seed))

    def _start(self) -> None:
        self._started = True
        op = self._op
        if op.kind in ("zip", "union"):
            bundles = self._drain_upstream()
            for b in self._staged._run_exchange(op, bundles):
                self._passthrough.append(b)
            return
        eager = self._eager_spec()
        if eager is not None:
            n, part_fn_of, post_fn = eager
            map_fn = _remote(_partition_map_segment)
            desc_refs, i = [], 0
            while True:
                b = self._upstream.next_bundle()
                if b is None:
                    break
                payload = cloudpickle.dumps((part_fn_of(i), n))
                desc_refs.append(
                    self._affine(map_fn, b).remote(payload, b.blocks_ref))
                i += 1
            if not desc_refs:
                return
            self._submit_reduces_segment(desc_refs, n, post_fn, False)
            return
        bundles = self._drain_upstream()
        if not bundles:
            return
        spec = self._staged.exchange_spec(op, bundles)
        if spec is None:  # degenerate exchange: inputs pass through
            self._passthrough.extend(bundles)
            return
        part_fns, n, post_fn, reverse = spec
        if self._segment:
            map_fn = _remote(_partition_map_segment)
            desc_refs = [
                self._affine(map_fn, b).remote(
                    cloudpickle.dumps((pf, n)), b.blocks_ref)
                for b, pf in zip(bundles, part_fns)
            ]
            self._submit_reduces_segment(desc_refs, n, post_fn, reverse)
        else:
            map_fn = _remote(_partition_map, num_returns=max(n, 1))
            part_refs = []
            for b, pf in zip(bundles, part_fns):
                refs = map_fn.remote(cloudpickle.dumps((pf, n)), b.blocks_ref)
                part_refs.append(refs if n > 1 else [refs])
            post_payload = cloudpickle.dumps(post_fn)
            reduce_fn = _remote(_exchange_reduce, num_returns=2)
            order = range(n - 1, -1, -1) if reverse else range(n)
            self._supply = iter([
                (lambda j=j: reduce_fn.remote(
                    post_payload, *[refs[j] for refs in part_refs]))
                for j in order
            ])

    def _affine(self, fn, bundle: RefBundle):
        """Exchange MAP tasks chase their input segment's node too — the
        partitioner re-reads the whole upstream bundle, so running it
        anywhere else turns every map input into cross-node traffic."""
        node = None
        if self._ctx.locality_placement and bundle.desc is not None:
            node = bundle.desc.get("node")
        self.stats.on_placement(node)
        if node:
            return fn.options(**locality.affinity_options(node))
        return fn

    def _drain_upstream(self) -> List[RefBundle]:
        t0 = time.monotonic_ns()
        bundles = []
        while True:
            b = self._upstream.next_bundle()
            if b is None:
                break
            bundles.append(b)
        t1 = time.monotonic_ns()
        flight.record("data.drain", t0, t1, lane=self.lane,
                      attrs={"bundles": len(bundles)})
        return bundles

    def _submit_reduces_segment(self, desc_refs, n, post_fn, reverse) -> None:
        post_payload = cloudpickle.dumps(post_fn)
        reduce_fn = _remote(_exchange_reduce_segments, num_returns=2)
        # Locality scoring needs the descriptor VALUES (per-partition byte
        # tables); they are the map results, so this get is the map-phase
        # barrier — small dicts, one batched round trip.
        descs = ray_get(desc_refs)
        for d in descs:
            self.stats.add_fetch(d.pop("fetch", None), group="exchange_map")
        nodes = (locality.segment_nodes(descs)
                 if self._ctx.locality_placement else [None] * len(descs))
        order = range(n - 1, -1, -1) if reverse else range(n)

        def submit(j: int):
            fn = reduce_fn
            node = (locality.best_node_for_partition(descs, j, nodes)
                    if self._ctx.locality_placement else None)
            if node is not None:
                fn = fn.options(**locality.affinity_options(node))
            self.stats.on_placement(node)
            return fn.remote(post_payload, j, *desc_refs)

        self._supply = iter([(lambda j=j: submit(j)) for j in order])

    # --------------------------------------------------------------- pull
    def _submit_one(self):
        if not self._started:
            self._start()
        if self._passthrough:
            return ("ready", self._passthrough.popleft())
        for thunk in self._supply:
            blocks_ref, meta_ref = thunk()
            return ("pair", blocks_ref, meta_ref)
        return None

    def next_bundle(self) -> Optional[RefBundle]:
        if self._done:
            return None
        self._refill()
        if self._pending and self._pending[0][0] == "ready":
            self.stats.on_yield(self.index, self._pending[0][1].num_rows,
                                self._pending[0][1].size_bytes, 0.0)
            return self._pending.popleft()[1]
        return super().next_bundle() if self._pending else self._finish()

    def _finish(self):
        self._done = True
        return None


# ------------------------------------------------------------ the executor
class PullExecutor:
    def __init__(self, ctx: Optional[DataContext] = None):
        self._ctx = ctx or DataContext.get_current()
        self.stats = StreamStats()

    def execute(self, plan: LogicalPlan) -> Iterator[RefBundle]:
        global _LAST_STATS
        _LAST_STATS = self.stats
        ctx = self._ctx
        window = ctx.streaming_window_blocks
        staged = StreamingExecutor(ctx)
        op: Optional[PhysicalOperator] = None
        idx = 0
        for src, chain in plan.segments():
            if isinstance(src, ReadOp):
                op = ReadSourceOp(idx, self.stats, window, ctx, src, chain)
                idx += 1
                continue  # chain is fused into the read tasks
            if isinstance(src, InputBlocksOp):
                op = InputOp(idx, self.stats, src.bundles)
                idx += 1
            elif isinstance(src, AllToAllOp):
                op = ExchangeOp(idx, self.stats, window, ctx, src, op, staged)
                idx += 1
            else:
                raise TypeError(f"Unknown segment source {src}")
            if chain:
                op = MapOp(idx, self.stats, window, ctx, op, chain)
                idx += 1
        return self._drive(op)

    def _drive(self, op: Optional[PhysicalOperator]) -> Iterator[RefBundle]:
        if op is None:
            self.stats.done()
            return
        try:
            while True:
                bundle = op.next_bundle()
                if bundle is None:
                    break
                bundle._on_release = self._released
                self.stats.on_deliver()
                yield bundle
        finally:
            self.stats.done()

    def _released(self, _bundle) -> None:
        self.stats.on_release()
