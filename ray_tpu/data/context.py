"""Global execution configuration (reference: `python/ray/data/context.py`)."""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


@dataclass
class ExecutionResources:
    cpu: Optional[float] = None
    gpu: Optional[float] = None
    object_store_memory: Optional[float] = None


@dataclass
class ExecutionOptions:
    resource_limits: ExecutionResources = field(default_factory=ExecutionResources)
    locality_with_output: bool = False
    preserve_order: bool = True
    verbose_progress: bool = False


@dataclass
class DataContext:
    """Process-wide dataset execution knobs.

    `max_in_flight_tasks` is the streaming-executor backpressure bound
    (reference: backpressure policies under
    `data/_internal/execution/backpressure_policy/`).
    """

    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_in_flight_tasks: int = max(2, (os.cpu_count() or 8))
    read_op_min_num_blocks: int = 8
    execution_options: ExecutionOptions = field(default_factory=ExecutionOptions)
    enable_progress_bars: bool = False
    eager_free: bool = True

    # Streaming pull plane (data/streaming/, docs/STREAMING_DATA.md).
    # streaming_pull routes Dataset._stream() through the bounded-window
    # PullExecutor; off = the legacy stage-barrier path (kept for A/B).
    streaming_pull: bool = field(
        default_factory=lambda: _env_bool("RAY_TPU_DATA_STREAMING_PULL", True))
    # Per-operator in-flight window: blocks resident (submitted but not yet
    # consumed+released) per op never exceeds this. Backpressure is pull-only
    # refill — an op pulls upstream only when its window has room, so the
    # bound propagates to the source with no explicit signaling.
    streaming_window_blocks: int = field(
        default_factory=lambda: _env_int("RAY_TPU_DATA_STREAMING_WINDOW", 8))
    # Route reduce/consumer tasks to the node holding the largest share of
    # their source segment bytes (soft node affinity via the controller's
    # candidate ordering; see data/streaming/locality.py).
    locality_placement: bool = field(
        default_factory=lambda: _env_bool("RAY_TPU_DATA_LOCALITY", True))
    # StreamingIngest per-rank prefetch queue depth (batches buffered ahead
    # of the training step; epoch N+1 production overlaps epoch N consume).
    ingest_prefetch_batches: int = field(
        default_factory=lambda: _env_int("RAY_TPU_DATA_INGEST_PREFETCH", 4))

    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
