"""Global execution configuration (reference: `python/ray/data/context.py`)."""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ExecutionResources:
    cpu: Optional[float] = None
    gpu: Optional[float] = None
    object_store_memory: Optional[float] = None


@dataclass
class ExecutionOptions:
    resource_limits: ExecutionResources = field(default_factory=ExecutionResources)
    locality_with_output: bool = False
    preserve_order: bool = True
    verbose_progress: bool = False


@dataclass
class DataContext:
    """Process-wide dataset execution knobs.

    `max_in_flight_tasks` is the streaming-executor backpressure bound
    (reference: backpressure policies under
    `data/_internal/execution/backpressure_policy/`).
    """

    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_in_flight_tasks: int = max(2, (os.cpu_count() or 8))
    read_op_min_num_blocks: int = 8
    execution_options: ExecutionOptions = field(default_factory=ExecutionOptions)
    enable_progress_bars: bool = False
    eager_free: bool = True

    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
