"""Block transport — exchange traffic over the arena-backed bulk planes.

The streaming executor's shuffle exchange used to move every partition as its
own pickled object put (`num_returns=P` map tasks → P×N small objects, each
fetched with a full object get). This module replaces that with ONE flat
segment per map task plus span-addressed reads:

  * the map task packs its P partitions into a single pickle-5 frame whose
    out-of-band buffers are the partitions' numpy columns, laid out
    contiguously (`serialization.pack` wire format:
    ``[u32 npayload][payload][u32 nbufs]{[u64 len][buffer]}*``). Because the
    transport serializes the frame itself (`ClusterBackend.put_serialized`),
    it knows every column's exact (offset, length) span inside the stored
    object and publishes a small DESCRIPTOR (span table + per-partition
    row/byte counts + the pinning ObjectRef) as the task's return value;
  * a reduce task for partition ``j`` resolves live copies via the
    controller's batched ``object_sources`` and pulls ONLY partition j's span
    from the source's bulk server (`core/bulk.py` wire protocol supports
    (name, offset, length) span requests natively) — cross-machine reduce
    traffic shrinks from whole-object pulls to exactly the bytes consumed;
  * on the SAME host the descriptor degrades to a plain ``ray_get`` of the
    segment, which rides the zero-copy borrow/map handover
    (`bulk_borrow`/`_pull_map`): the rebuilt columns are numpy views over
    the source arena mapping — no copy at all.

Fallbacks (always correctness-preserving, see data/README.md):
  * backend without ``put_serialized`` (local mode, remote client) → plain
    ``ray_put`` of the partition list, spans absent;
  * non-columnar (simple list) partitions, object-dtype or structured
    columns → that partition is carried in-band in the pickle payload and
    fetched via ``ray_get``;
  * any span-fetch failure (source moved/evicted/spilled mid-read, bulk
    endpoint gone) → ``ray_get`` of the whole segment.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import socket
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import api
from ..core import bulk as bulk_mod
from ..core import config as rt_config
from ..core import serialization
from ..core.api import get as ray_get, put as ray_put
from .block import Block, BlockAccessor, is_columnar

DESCRIPTOR_VERSION = 1


def transport_enabled() -> bool:
    """Whether exchange traffic should ride block segments at all (the
    pickled-put path remains selectable for A/B measurement —
    `scripts/bench_data.py` records both)."""
    return bool(rt_config.get("data_block_transport"))


def node_strict() -> bool:
    """Cross-node reads decided by NODE ID instead of host IP. On a real
    multi-machine cluster the two agree; on a one-box multi-node cluster
    (`cluster_utils.Cluster`, `bench_data --nodes N`) every node shares the
    host IPs AND /dev/shm, so the opportunistic local-arena read would
    silently serve "cross-node" segments zero-copy and the TCP bulk path
    would never be measured. Strict mode makes the one-box cluster behave
    byte-for-byte like a real multi-machine one: only segments produced on
    THIS logical node read locally, everything else rides span pulls."""
    return bool(rt_config.get("data_node_strict"))


def local_node_id() -> str:
    """This process's logical node id (worker env / backend registration)."""
    return os.environ.get("RAY_TPU_NODE_ID", "node0")


# ------------------------------------------------------------- fetch rungs
# Per-rung fetch accounting: every descriptor consumption lands on exactly
# one rung, so "no silent fallback to whole-object gets" is ASSERTABLE
# (tests/test_data_transport.py) instead of trusted. Counters are process
# global; `track_fetch()` additionally captures a thread-scoped delta so a
# reduce/consumer task can ship ITS rung counts back in task metadata
# (`_exchange_reduce_segments` → meta["fetch"] → StreamStats).
FETCH_RUNGS = ("inline", "local", "span", "get", "empty")
_STATS_LOCK = threading.Lock()


def _zero_stats() -> Dict[str, int]:
    d = {r: 0 for r in FETCH_RUNGS}
    d.update(local_bytes=0, span_bytes=0, get_bytes=0, cross_node_bytes=0)
    return d


_FETCH_STATS = _zero_stats()
_TRACK = threading.local()


def _count(rung: str, n: int = 1, **bytes_kw: int) -> None:
    with _STATS_LOCK:
        sinks = [_FETCH_STATS] + list(getattr(_TRACK, "stack", ()))
        for d in sinks:
            d[rung] = d.get(rung, 0) + n
            for k, v in bytes_kw.items():
                d[k] = d.get(k, 0) + v


def fetch_stats() -> Dict[str, int]:
    """Process-global rung counters (copy)."""
    with _STATS_LOCK:
        return dict(_FETCH_STATS)


def reset_fetch_stats() -> None:
    with _STATS_LOCK:
        _FETCH_STATS.clear()
        _FETCH_STATS.update(_zero_stats())


@contextlib.contextmanager
def track_fetch():
    """Capture the rung counts of every fetch on THIS thread inside the
    body (nested trackers both see them). Yields the mutating dict."""
    d = _zero_stats()
    stack = getattr(_TRACK, "stack", None)
    if stack is None:
        stack = _TRACK.stack = []
    stack.append(d)
    try:
        yield d
    finally:
        stack.remove(d)


def merge_fetch_stats(into: Dict[str, int], delta: Optional[Dict[str, int]]) -> None:
    """Accumulate one task's rung delta into an aggregate dict."""
    for k, v in (delta or {}).items():
        if isinstance(v, (int, float)):
            into[k] = into.get(k, 0) + v


# ------------------------------------------------------------ serialization
def _rebuild_col(dtype_str: str, shape, buf) -> np.ndarray:
    """Out-of-band column reconstruction: a zero-copy view over whatever
    buffer the unpickler hands us (the arena mapping on a local read)."""
    arr = np.frombuffer(buf, dtype=np.dtype(dtype_str))
    return arr.reshape(shape)


class _OOBColumn:
    """Wraps one contiguous numpy column so its bytes travel as ONE
    out-of-band pickle-5 buffer at a knowable frame offset. Unpickles
    straight to the ndarray (callers never see the wrapper)."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray):
        self.arr = arr

    def __reduce__(self):
        return (
            _rebuild_col,
            (self.arr.dtype.str, self.arr.shape, pickle.PickleBuffer(self.arr)),
        )


def _rebuild_inband(data: bytes):
    import cloudpickle

    return cloudpickle.loads(data)


class _InbandPart:
    """A partition the span layout cannot carry (simple blocks, object
    columns): pre-pickled to BYTES so it stays entirely in the in-band
    payload — it must never emit out-of-band buffers of its own, or the
    buffer→column index mapping below would silently misalign."""

    __slots__ = ("data",)

    def __init__(self, part):
        import cloudpickle

        self.data = cloudpickle.dumps(part)

    def __reduce__(self):
        return (_rebuild_inband, (self.data,))


def _spannable(part: List[Block]) -> bool:
    for blk in part:
        if not is_columnar(blk):
            return False
        for v in blk.values():
            if not isinstance(v, np.ndarray) or v.dtype.hasobject or v.dtype.fields:
                return False
    return True


# ------------------------------------------------------------------ producer
def put_partitions(parts: List[List[Block]]) -> Dict[str, Any]:
    """Pack per-partition block lists into one segment object; returns the
    descriptor (small, pickles into the task's normal return value). The
    descriptor's nested ObjectRef keeps the segment pinned for as long as
    any holder of the descriptor lives (contained-ref tracking)."""
    rows = [sum(BlockAccessor(b).num_rows() for b in p) for p in parts]
    sizes = [sum(BlockAccessor(b).size_bytes() for b in p) for p in parts]
    rt = api._global_runtime()
    backend = rt.backend
    put_serialized = getattr(backend, "put_serialized", None)
    if put_serialized is None or getattr(backend, "remote_client", False):
        return {"v": DESCRIPTOR_VERSION, "ref": ray_put(parts),
                "rows": rows, "bytes": sizes, "spans": None,
                "node": local_node_id()}

    wrapped: List[Any] = []
    part_cols: List[Optional[List[np.ndarray]]] = []  # pickle-order columns
    for part in parts:
        if not _spannable(part):
            wrapped.append(_InbandPart(part))
            part_cols.append(None)
            continue
        wp, cols = [], []
        for blk in part:
            nd = {}
            for k, v in blk.items():
                arr = np.ascontiguousarray(v)
                nd[k] = _OOBColumn(arr)
                cols.append(arr)
            wp.append(nd)
        wrapped.append(wp)
        part_cols.append(cols)

    payload, buffers = serialization.serialize(wrapped)
    # Buffer k ↔ the k-th wrapped column, in partition/block/column traversal
    # order (pickle walks lists and dicts in order; _InbandPart partitions
    # contribute none by construction). A count mismatch means something
    # unexpected went out-of-band — drop the span table, keep the object.
    expected = sum(len(c) for c in part_cols if c is not None)
    spans = None
    if len(buffers) == expected:
        # Frame layout: [u32 npayload][payload][u32 nbufs] then per buffer
        # [u64 len][bytes]; data offset of buffer k is computable up front.
        cur = 4 + len(payload) + 4
        buf_offs = []
        for b in buffers:
            n = b.raw().nbytes
            buf_offs.append((cur + 8, n))
            cur += 8 + n
        spans = []
        k = 0
        for part, cols in zip(parts, part_cols):
            if cols is None:
                spans.append(None)
                continue
            n_cols = len(cols)
            first = buf_offs[k][0] if n_cols else 0
            end = (buf_offs[k + n_cols - 1][0] +
                   buf_offs[k + n_cols - 1][1]) if n_cols else 0
            blocks_meta = []
            ki = k
            for blk in part:
                cols_meta = []
                for name in blk.keys():
                    arr = cols[ki - k]
                    off, nb = buf_offs[ki]
                    cols_meta.append(
                        (name, arr.dtype.str, arr.shape, off - first, nb)
                    )
                    ki += 1
                blocks_meta.append(cols_meta)
            spans.append({"off": first, "len": end - first,
                          "blocks": blocks_meta})
            k += n_cols

    ref, name, span_ok = put_serialized(payload, buffers,
                                        rt.current_task_id.hex())
    if not span_ok:
        spans = None  # inline frame: span-addressed reads are impossible
    return {"v": DESCRIPTOR_VERSION, "ref": ref, "name": name, "rows": rows,
            "bytes": sizes, "spans": spans, "node": local_node_id(),
            "inline": not span_ok}


# ------------------------------------------------------- ONE-TO-ONE bundles
# Map/read outputs in the streaming plane are single-partition segments: the
# task returns `put_bundle(blocks)`'s descriptor instead of the block list,
# and whoever consumes the bundle (a chained map task, a reduce task's
# partitioner, the driver-side iterator) resolves it through the SAME rung
# ladder the exchange uses. `resolve_blocks` is the universal kernel-entry
# shim: block lists pass through untouched, so every kernel handles both
# transports with one line.
_BUNDLE_KEY = "b1"


def put_bundle(blocks: List[Block]) -> Dict[str, Any]:
    """Pack ONE output's blocks as a single-partition segment descriptor."""
    desc = put_partitions([blocks])
    desc[_BUNDLE_KEY] = True
    return desc


def is_descriptor(x: Any) -> bool:
    return isinstance(x, dict) and x.get(_BUNDLE_KEY) is True and "ref" in x


def fetch_bundle(desc: Dict[str, Any]) -> List[Block]:
    """Materialize a ONE-TO-ONE bundle descriptor's blocks (rung-counted)."""
    return fetch_partition(desc, 0)


def resolve_blocks(x: Any) -> List[Block]:
    """Kernel-entry shim: descriptor → fetched blocks, block list → itself."""
    if is_descriptor(x):
        return fetch_bundle(x)
    return x


# ------------------------------------------------------------------ consumer
def _try_local_read(desc: Dict[str, Any]):
    """Zero-RPC fast path: the descriptor names the segment in the producer
    node's shared store — a consumer on the SAME node deserializes it
    straight off the arena mapping, exactly like the deps-map fast path
    resolves classic task args (no controller round trip, no blocked-worker
    lease dance). Returns the partition list or None when the segment is not
    readable here (other node, evicted, spilled — callers fall back)."""
    name = desc.get("name")
    if not name:
        return None
    if node_strict() and desc.get("node") not in (None, local_node_id()):
        # One-box multi-node: the name WOULD resolve in /dev/shm, but on a
        # real cluster this segment lives on another machine. Refuse.
        return None
    backend = api._global_runtime().backend
    local_store = getattr(backend, "local_store", None)
    if local_store is None:
        return None
    try:
        return local_store.read(name)
    except Exception:  # noqa: BLE001 — not local / gone; resolve properly
        return None


def _fetch_span(addr: str, name: str, offset: int, length: int,
                tmo: float) -> bytearray:
    """Pull one (offset, length) span of a stored object from a peer's bulk
    server into private memory (partition-sized — not a store object).
    Shared wire front with the KV-transfer plane: `bulk.fetch_span_bytes`."""
    return bulk_mod.fetch_span_bytes(addr, name, offset, length, tmo)


def _rebuild_from_span(span: Dict[str, Any], buf: bytearray) -> List[Block]:
    view = memoryview(buf)
    out: List[Block] = []
    for cols_meta in span["blocks"]:
        blk: Dict[str, np.ndarray] = {}
        for name, dtype_str, shape, rel_off, nbytes in cols_meta:
            blk[name] = _rebuild_col(
                dtype_str, tuple(shape), view[rel_off:rel_off + nbytes]
            )
        out.append(blk)
    return out


def fetch_partition(desc: Dict[str, Any], j: int) -> List[Block]:
    """Partition ``j`` of one segment descriptor (see fetch_partitions)."""
    return fetch_partitions([desc], j)[0]


def fetch_partitions(descs: List[Dict[str, Any]], j: int) -> List[List[Block]]:
    """Partition ``j`` of EVERY map segment, batched: one controller round
    trip resolves all sources, local segments materialize in one batched get
    (zero-copy borrow/map on this host), and remote spans pull concurrently.
    Any per-segment failure degrades that segment to a whole-object get —
    per-object RPC round trips, not bytes, dominated small exchanges, so
    everything here is one-RPC-per-stage, not per-object."""
    out: List[Optional[List[Block]]] = [None] * len(descs)
    spannable: List[int] = []  # desc indices that could take the span path
    for i, desc in enumerate(descs):
        spans = desc.get("spans")
        if spans is not None and spans[j] is not None and not spans[j]["blocks"]:
            out[i] = []  # empty partition: nothing to fetch at all
            _count("empty")
            continue
        parts = _try_local_read(desc)
        if parts is not None:
            out[i] = parts[j]  # same-node segment: zero-copy, zero RPCs
            _count("local", local_bytes=int(desc["bytes"][j]))
            continue
        if spans is None or spans[j] is None:
            continue  # resolved via the batched get below
        spannable.append(i)

    backend = api._global_runtime().backend
    sources_of = getattr(backend, "object_sources", None)
    remote: List[int] = []
    srcs: Dict[int, dict] = {}
    same_host: set = set()
    if spannable and sources_of is not None:
        resolved = sources_of([descs[i]["ref"].id.hex() for i in spannable])
        local_addrs = bulk_mod._local_addrs()
        strict = node_strict()
        here = local_node_id()
        for i, src in zip(spannable, resolved):
            if not src:
                continue  # unresolvable — batched get below
            if strict:
                # Node identity, not host IP: on a one-box cluster every
                # node shares the IPs, so this is what keeps "cross-node"
                # honest (segments from other logical nodes ride TCP spans).
                cross = src.get("node") not in (None, here)
            else:
                cross = src["bulk"].rsplit(":", 1)[0] not in local_addrs
            if cross:
                remote.append(i)
                srcs[i] = src
            else:
                # Same host (borrow/map handover beats a TCP span copy) —
                # materializes via the batched get below but rung-wise it IS
                # the same-node zero-copy path.
                same_host.add(i)

    if remote:
        tmo = rt_config.get("transfer_chunk_timeout_s")
        def pull(i: int):
            span = descs[i]["spans"][j]
            try:
                buf = _fetch_span(srcs[i]["bulk"], srcs[i]["name"],
                                  span["off"], span["len"], tmo)
            except (OSError, RuntimeError, socket.timeout):
                # Source died/evicted mid-read: the controller's directory
                # still knows other copies (or re-executes lineage) — the
                # plain get path below absorbs all of that.
                return None
            _count("span", span_bytes=span["len"],
                   cross_node_bytes=span["len"])
            return _rebuild_from_span(span, buf)

        if len(remote) == 1:
            results = [pull(remote[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor

            # The rung tracker stack is thread-local: graft the CALLER's
            # stack onto each (fresh, per-call) pool thread, or concurrent
            # span pulls vanish from the task's shipped fetch delta.
            caller_stack = list(getattr(_TRACK, "stack", ()))

            def pull_tracked(i: int):
                _TRACK.stack = caller_stack
                return pull(i)

            with ThreadPoolExecutor(
                max_workers=min(4, len(remote)),
                thread_name_prefix="rtpu-span-fetch",
            ) as ex:
                results = list(ex.map(pull_tracked, remote))
        for i, res in zip(remote, results):
            out[i] = res

    pending = [i for i, res in enumerate(out) if res is None]
    if pending:
        # One batched get for every whole-segment materialization (local
        # zero-copy reads + any span-fetch fallbacks).
        values = ray_get([descs[i]["ref"] for i in pending])
        for i, parts in zip(pending, values):
            out[i] = parts[j]
            nbytes = int(descs[i]["bytes"][j])
            if descs[i].get("inline"):
                _count("inline")  # rode the inline plane; no arena segment
            elif i in same_host:
                _count("local", local_bytes=nbytes)  # zero-copy borrow/map
            else:
                _count("get", get_bytes=nbytes)
    return out  # type: ignore[return-value]
