"""Logical plan: one-to-one transforms + all-to-all boundaries.

Reference: `python/ray/data/_internal/logical/` (logical operators) and
`_internal/planner/` (fusion). Consecutive one-to-one ops are fused into a
single *chain* executed inside one remote task per block — the reference
does the same fusion (`TaskPoolMapOperator` fusion rules) so a
read→map→filter pipeline costs one task per block, not three.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from .block import (
    Block,
    BlockAccessor,
    build_block,
    concat_blocks,
    is_columnar,
    rows_to_block,
)


class Op:
    """Base logical operator."""

    name = "Op"


# ------------------------------------------------------------- one-to-one
class OneToOneOp(Op):
    """Transforms a stream of blocks within one task (fusable)."""

    def apply(self, blocks: List[Block]) -> List[Block]:
        raise NotImplementedError


@dataclass
class MapBatches(OneToOneOp):
    fn: Callable
    batch_size: Optional[int] = None
    batch_format: Optional[str] = "default"
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    fn_constructor_args: tuple = ()
    is_callable_class: bool = False
    name = "MapBatches"

    def _callable(self):
        if self.is_callable_class:
            fn = self.fn(*self.fn_constructor_args)
        else:
            fn = self.fn
        return fn

    def apply(self, blocks: List[Block]) -> List[Block]:
        fn = self._callable()
        out: List[Block] = []
        for batch in _rebatch(blocks, self.batch_size):
            acc = BlockAccessor(batch)
            res = fn(acc.to_batch(self.batch_format), *self.fn_args, **self.fn_kwargs)
            out.append(build_block(res))
        return out


def _rebatch(blocks: List[Block], batch_size: Optional[int]):
    """Yield batches of exactly `batch_size` rows (last may be short)."""
    if batch_size is None:
        for b in blocks:
            if BlockAccessor(b).num_rows() > 0:
                yield b
        return
    buf: List[Block] = []
    buffered = 0
    for b in blocks:
        acc = BlockAccessor(b)
        n = acc.num_rows()
        start = 0
        while start < n:
            take = min(batch_size - buffered, n - start)
            buf.append(acc.slice(start, start + take))
            buffered += take
            start += take
            if buffered == batch_size:
                yield concat_blocks(buf)
                buf, buffered = [], 0
    if buffered:
        yield concat_blocks(buf)


@dataclass
class MapRows(OneToOneOp):
    fn: Callable
    name = "Map"

    def apply(self, blocks):
        out = []
        for b in blocks:
            rows = [self.fn(r) for r in BlockAccessor(b).iter_rows()]
            if rows and all(isinstance(r, dict) for r in rows):
                out.append(rows_to_block(rows))
            else:
                out.append(list(rows))
        return out


@dataclass
class FlatMap(OneToOneOp):
    fn: Callable
    name = "FlatMap"

    def apply(self, blocks):
        out = []
        for b in blocks:
            rows: List[Any] = []
            for r in BlockAccessor(b).iter_rows():
                rows.extend(self.fn(r))
            if rows and all(isinstance(r, dict) for r in rows):
                out.append(rows_to_block(rows))
            elif rows:
                out.append(list(rows))
        return out


@dataclass
class Filter(OneToOneOp):
    fn: Callable
    name = "Filter"

    def apply(self, blocks):
        out = []
        for b in blocks:
            acc = BlockAccessor(b)
            if is_columnar(b):
                mask = np.asarray([bool(self.fn(r)) for r in acc.iter_rows()])
                if mask.any():
                    out.append(acc.take(np.nonzero(mask)[0]))
            else:
                kept = [r for r in b if self.fn(r)]
                if kept:
                    out.append(kept)
        return out


@dataclass
class LimitOp(OneToOneOp):
    n: int
    name = "Limit"

    def apply(self, blocks):
        out, remaining = [], self.n
        for b in blocks:
            if remaining <= 0:
                break
            acc = BlockAccessor(b)
            take = min(acc.num_rows(), remaining)
            out.append(acc.slice(0, take))
            remaining -= take
        return out


@dataclass
class SelectColumns(OneToOneOp):
    cols: List[str]
    name = "SelectColumns"

    def apply(self, blocks):
        return [{k: b[k] for k in self.cols} for b in blocks]


@dataclass
class DropColumns(OneToOneOp):
    cols: List[str]
    name = "DropColumns"

    def apply(self, blocks):
        return [{k: v for k, v in b.items() if k not in self.cols} for b in blocks]


@dataclass
class AddColumn(OneToOneOp):
    col: str
    fn: Callable  # batch(dict) -> np.ndarray
    name = "AddColumn"

    def apply(self, blocks):
        out = []
        for b in blocks:
            b = dict(b)
            b[self.col] = np.asarray(self.fn(b))
            out.append(b)
        return out


@dataclass
class RenameColumns(OneToOneOp):
    mapping: Dict[str, str]
    name = "RenameColumns"

    def apply(self, blocks):
        return [{self.mapping.get(k, k): v for k, v in b.items()} for b in blocks]


# ------------------------------------------------------------- all-to-all
@dataclass
class AllToAllOp(Op):
    """Materialization boundary handled by the executor's exchange."""

    kind: str  # repartition | random_shuffle | sort | groupby | zip | union
    num_outputs: Optional[int] = None
    key: Union[None, str, List[str]] = None
    descending: bool = False
    seed: Optional[int] = None
    aggs: Optional[List[Any]] = None
    other_plans: Optional[List[Any]] = None  # for zip/union
    shuffle: bool = False
    name = "AllToAll"


@dataclass
class ReadOp(Op):
    datasource: Any
    parallelism: int = -1
    name = "Read"


@dataclass
class InputBlocksOp(Op):
    """Plan rooted at pre-existing block refs (post-exchange or materialized)."""

    bundles: List[Any]  # List[RefBundle]
    name = "InputBlocks"


class LogicalPlan:
    def __init__(self, ops: List[Op]):
        self.ops = ops

    def with_op(self, op: Op) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def segments(self) -> List[Tuple[Op, List[OneToOneOp]]]:
        """Split into (source-or-exchange, fused one-to-one chain) segments."""
        assert self.ops and isinstance(self.ops[0], (ReadOp, InputBlocksOp))
        segs: List[Tuple[Op, List[OneToOneOp]]] = []
        current_src: Op = self.ops[0]
        chain: List[OneToOneOp] = []
        for op in self.ops[1:]:
            if isinstance(op, OneToOneOp):
                chain.append(op)
            else:
                segs.append((current_src, chain))
                current_src, chain = op, []
        segs.append((current_src, chain))
        return segs


def apply_chain(chain: List[OneToOneOp], blocks: List[Block]) -> List[Block]:
    for op in chain:
        blocks = op.apply(blocks)
    return blocks
