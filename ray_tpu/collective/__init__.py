"""Collective communication (API parity: `ray.util.collective.collective`).

The reference wires NCCL/Gloo communicators between actor processes
(`collective.py:120 init_collective_group`, ops at `:258-615`). TPU-first
redesign — THREE planes, matching SURVEY.md §5:

1. **In-jit (ICI)**: `ops.allreduce(x, axis="dp")` etc. lower to
   `jax.lax.p*` inside a jitted program over a Mesh — the "communicator" is
   the XLA compiler. This is where tensor traffic belongs on TPU.
2. **Host-level group collectives (DCN analog)**: the `ray.util.collective`
   actor-group API (`init_collective_group` / `allreduce(tensor, group)`)
   implemented over the object store through a rendezvous actor — for
   control-plane-sized arrays (weight broadcast, metric reduction) between
   gang actors, exactly the role Gloo plays in the reference.
3. **Multi-host jax runtime bootstrap**: `init_jax_distributed` arranges
   `jax.distributed.initialize` across a WorkerGroup so a multi-host mesh
   can be built (the moral equivalent of `dist.init_process_group` in
   `train/torch/config.py:106`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import ops
from .ops import (
    all_gather,
    all_to_all,
    allreduce_jit,
    barrier_jit,
    ppermute,
    psum,
    reduce_scatter,
)


class Backend:
    XLA = "xla"      # in-jit, over ICI — the TPU-native plane
    HOST = "host"    # object-store host collectives (Gloo role)
    # Aliases for reference API compatibility; both map to HOST on CPU paths.
    GLOO = "host"
    NCCL = "xla"


class GroupInfo:
    """Rendezvous + reduction state for one collective group (detached actor).

    Reference analog: the named "Info" actor storing NCCL unique IDs
    (`collective.py:40 GroupManager`). Here it is also the data plane for
    host collectives: members push chunks, the actor reduces and serves.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.members: Dict[int, bool] = {}
        self._rounds: Dict[str, dict] = {}

    def join(self, rank: int) -> int:
        self.members[rank] = True
        return len(self.members)

    def ready(self) -> bool:
        return len(self.members) >= self.world_size

    def _round(self, key: str) -> dict:
        r = self._rounds.get(key)
        if r is None:
            r = self._rounds[key] = {"parts": {}, "result": None, "fetched": 0}
        return r

    def contribute(self, key: str, rank: int, value, op: str, root: int = 0):
        """Accumulate a member's tensor for round `key`; returns #arrived."""
        r = self._round(key)
        r["parts"][rank] = value
        if op == "p2p":
            return len(r["parts"])
        if len(r["parts"]) == self.world_size:
            vals = [r["parts"][k] for k in sorted(r["parts"])]
            if op == "sum":
                out = vals[0]
                for v in vals[1:]:
                    out = out + v
            elif op == "max":
                out = np.maximum.reduce(vals)
            elif op == "min":
                out = np.minimum.reduce(vals)
            elif op == "prod":
                out = np.multiply.reduce(vals)
            elif op == "gather":
                out = vals
            elif op == "broadcast":
                out = r["parts"][root]
            else:
                raise ValueError(f"unknown op {op}")
            r["result"] = out
        return len(r["parts"])

    def fetch(self, key: str):
        r = self._round(key)
        if r["result"] is None:
            return None
        result = r["result"]
        r["fetched"] += 1
        if r["fetched"] >= self.world_size:
            self._rounds.pop(key, None)  # all members served — free the round
        return result

    def discard(self, key: str):
        self._rounds.pop(key, None)

    def fetch_p2p(self, key: str):
        """One-shot point-to-point mailbox read (consumes the value)."""
        r = self._rounds.get(key)
        if r is None or not r["parts"]:
            return None
        self._rounds.pop(key, None)
        return next(iter(r["parts"].values()))


_LOCAL = threading.local()


def _info_actor(group_name: str, world_size: Optional[int] = None, create: bool = False):
    from .. import core
    from ..core import api

    name = f"__collective_{group_name}"
    handle = api.get_actor_or_none(name)
    if handle is None and create:
        remote_cls = api.remote(GroupInfo)
        try:
            handle = remote_cls.options(name=name, lifetime="detached").remote(world_size)
        except ValueError:
            handle = api.get_actor(name)
    if handle is None:
        raise ValueError(f"Collective group '{group_name}' does not exist")
    return handle


def _ctx() -> dict:
    if not hasattr(_LOCAL, "groups"):
        _LOCAL.groups = {}
    return _LOCAL.groups


_VALID_BACKENDS = {"host", "gloo", "xla", "nccl"}


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = Backend.HOST,
    group_name: str = "default",
):
    """Called by each member (inside its actor/task) to join a group."""
    from ..core import api

    b = str(backend).lower()
    if b not in _VALID_BACKENDS:
        raise ValueError(f"Unknown collective backend {backend!r}; valid: {_VALID_BACKENDS}")
    if b in ("xla", "nccl"):
        import warnings

        warnings.warn(
            "Device-plane collectives on TPU compile into jit programs "
            "(ray_tpu.collective.ops.* under shard_map/pjit); group "
            f"'{group_name}' will use the host plane for out-of-jit arrays.",
            stacklevel=2,
        )
    info = _info_actor(group_name, world_size, create=True)
    api.get(info.join.remote(rank))
    deadline = time.time() + 60
    while not api.get(info.ready.remote()):
        if time.time() > deadline:
            raise TimeoutError(f"Group {group_name} rendezvous timed out")
        time.sleep(0.02)
    _ctx()[group_name] = {"info": info, "rank": rank, "world_size": world_size, "seq": 0}


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = Backend.HOST,
    group_name: str = "default",
):
    """Declarative variant (reference `collective.py:151`): the driver
    assigns ranks; actors must expose `init_collective_group` calls in their
    methods (or use `ray_tpu.collective.init_collective_group` inside)."""
    _info_actor(group_name, world_size, create=True)
    return True


def destroy_collective_group(group_name: str = "default"):
    from ..core import api

    try:
        handle = api.get_actor_or_none(f"__collective_{group_name}")
        if handle is not None:
            api.kill(handle)
    finally:
        _ctx().pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    g = _ctx().get(group_name)
    return g["rank"] if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _ctx().get(group_name)
    return g["world_size"] if g else -1


def _sync(group_name: str, op: str, value, root: int = 0):
    from ..core import api

    g = _ctx().get(group_name)
    if g is None:
        raise RuntimeError(
            f"init_collective_group('{group_name}') must be called in this process first"
        )
    g["seq"] += 1
    key = f"{op}:{g['seq']}"
    info = g["info"]
    api.get(info.contribute.remote(key, g["rank"], value, op, root))
    deadline = time.time() + 300
    while True:
        result = api.get(info.fetch.remote(key))
        if result is not None:
            return result
        if time.time() > deadline:
            raise TimeoutError(f"collective {op} timed out in group {group_name}")
        time.sleep(0.005)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """Host-plane allreduce (reference `collective.py:258`). For tensors that
    live on-device inside jit, use `ops.psum`/`allreduce_jit` instead.

    Results are defensive copies: in local mode the object table stores by
    reference, and members must never alias each other's arrays.
    """
    return np.array(_sync(group_name, op, np.asarray(tensor)), copy=True)


def allgather(tensor, group_name: str = "default"):
    return [
        np.array(v, copy=True)
        for v in _sync(group_name, "gather", np.asarray(tensor))
    ]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return np.array(
        _sync(group_name, "broadcast", np.asarray(tensor), root=src_rank), copy=True
    )


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    g = _ctx()[group_name]
    total = np.array(_sync(group_name, op, np.asarray(tensor)), copy=True)
    chunks = np.array_split(total, g["world_size"], axis=0)
    return chunks[g["rank"]]


def barrier(group_name: str = "default"):
    _sync(group_name, "sum", np.zeros((), np.int32))


def _p2p_key(g: dict, src: int, dst: int) -> str:
    # Both endpoints count their mutual transfers, so the pair's keys line up
    # regardless of what other collectives either side ran in between.
    p2p = g.setdefault("p2p", {})
    p2p[(src, dst)] = p2p.get((src, dst), 0) + 1
    return f"p2p:{src}->{dst}:{p2p[(src, dst)]}"


def send(tensor, dst_rank: int, group_name: str = "default"):
    """Point-to-point via the group actor (host plane)."""
    from ..core import api

    g = _ctx()[group_name]
    key = _p2p_key(g, g["rank"], dst_rank)
    api.get(g["info"].contribute.remote(key, 0, np.asarray(tensor), "p2p"))


def recv(src_rank: int, group_name: str = "default"):
    from ..core import api

    g = _ctx()[group_name]
    key = _p2p_key(g, src_rank, g["rank"])
    info = g["info"]
    deadline = time.time() + 300
    while True:
        result = api.get(info.fetch_p2p.remote(key))
        if result is not None:
            return np.array(result, copy=True)
        if time.time() > deadline:
            raise TimeoutError("recv timed out")
        time.sleep(0.005)


__all__ = [
    "Backend",
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allgather",
    "broadcast",
    "reducescatter",
    "barrier",
    "send",
    "recv",
    # in-jit plane
    "ops",
    "psum",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "ppermute",
    "allreduce_jit",
    "barrier_jit",
]
