"""Collective communication (API parity: `ray.util.collective.collective`).

The reference wires NCCL/Gloo communicators between actor processes
(`collective.py:120 init_collective_group`, ops at `:258-615`). TPU-first
redesign — THREE planes, matching SURVEY.md §5:

1. **In-jit (ICI)**: `ops.allreduce(x, axis="dp")` etc. lower to
   `jax.lax.p*` inside a jitted program over a Mesh — the "communicator" is
   the XLA compiler. This is where tensor traffic belongs on TPU.
2. **Host-level group collectives (DCN analog)**: the `ray.util.collective`
   actor-group API over the OBJECT STORE. The rendezvous actor exchanges
   only ObjectRefs and blocks members on round completion (no payload ever
   transits the actor, no busy-polling) — the reference's rendezvous-only
   pattern (`nccl_collective_group.py:132-155`, where the Info actor stores
   NCCL ids and data rides NCCL). Tensors move peer-to-peer through the
   store; large-world allreduce uses bandwidth-optimal reduce-scatter +
   allgather (per-member traffic ~3×size instead of world×size).
3. **Multi-host jax runtime bootstrap**: `init_jax_distributed` arranges
   `jax.distributed.initialize` across a WorkerGroup so a multi-host mesh
   can be built (the moral equivalent of `dist.init_process_group` in
   `train/torch/config.py:106`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import ops
from .ops import (
    all_gather,
    all_to_all,
    allreduce_jit,
    barrier_jit,
    ppermute,
    psum,
    reduce_scatter,
)


class Backend:
    XLA = "xla"      # in-jit, over ICI — the TPU-native plane
    HOST = "host"    # object-store host collectives (Gloo role)
    # Aliases for reference API compatibility; both map to HOST on CPU paths.
    GLOO = "host"
    NCCL = "xla"


class GroupRendezvous:
    """Control-plane-only rendezvous for one collective group (detached
    actor, max_concurrency sized to the world so members can BLOCK in
    `contribute_and_await` — long-poll semantics, no client-side spinning).

    Carries ObjectRefs (and rank bookkeeping) exclusively; tensor bytes
    stay in the object store."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._members: Dict[int, bool] = {}
        self._ready = threading.Event()
        self._rounds: Dict[str, dict] = {}
        self._rank_map: Dict[str, int] = {}  # actor hex -> assigned rank

    # ------------------------------------------------------------ membership
    def join(self, rank: int) -> int:
        with self._lock:
            self._members[rank] = True
            n = len(self._members)
            if n >= self.world_size:
                self._ready.set()
        return n

    def await_ready(self, timeout: float = 60.0) -> bool:
        return self._ready.wait(timeout)

    def assign_ranks(self, mapping: Dict[str, int]) -> bool:
        with self._lock:
            self._rank_map.update(mapping)
        return True

    def assigned_rank(self, actor_hex: str) -> int:
        with self._lock:
            return self._rank_map.get(actor_hex, -1)

    def get_world_size(self) -> int:
        return self.world_size

    # ---------------------------------------------------------------- rounds
    def _round(self, key: str) -> dict:
        r = self._rounds.get(key)
        if r is None:
            r = self._rounds[key] = {
                "refs": {},
                "event": threading.Event(),
                "served": 0,
            }
        return r

    def contribute_and_await(self, key: str, rank: int, ref, timeout: float = 300.0):
        """Deposit this member's ref for round `key`, then BLOCK until every
        member has contributed. Returns {rank: ref} or None on timeout.

        A timeout ABORTS the round for everyone (symmetric failure): the
        waiters that timed out and any straggler arriving later all get
        None, and the round's refs are dropped — no member computes a
        result others missed, and nothing leaks in the actor."""
        with self._lock:
            r = self._round(key)
            if r.get("aborted"):
                # Tombstone: fail fast; reclaim once every member observed it.
                r["served"] += 1
                if r["served"] >= self.world_size:
                    self._rounds.pop(key, None)
                return None
            r["refs"][rank] = ref
            if len(r["refs"]) >= self.world_size:
                r["event"].set()
        if not r["event"].wait(timeout):
            with self._lock:
                r["aborted"] = True
                r["event"].set()  # release other waiters into the abort path
                r["refs"].clear()  # drop payload refs; KEEP the tombstone so
                # a straggler arriving later fails fast instead of opening a
                # fresh round and stalling its own full timeout.
                r["served"] += 1
                if r["served"] >= self.world_size:
                    self._rounds.pop(key, None)
            return None
        with self._lock:
            if r.get("aborted"):
                r["served"] += 1
                if r["served"] >= self.world_size:
                    self._rounds.pop(key, None)
                return None
            refs = dict(r["refs"])
            r["served"] += 1
            if r["served"] >= self.world_size:
                self._rounds.pop(key, None)  # all members served — free refs
        return refs

    # Tombstoned (aborted) rounds kept so stragglers fail fast instead of
    # re-opening the round and wedging; beyond this many table entries the
    # oldest tombstones are dropped — dead members never bump `served`, so
    # without a bound repeated aborts would leak entries in this detached
    # actor forever.
    _MAX_ROUNDS = 1024

    def abort_rounds(self) -> int:
        """Abort every in-progress round: waiters blocked in
        contribute_and_await are released into the None/abort path, and
        stragglers that have not contributed yet fail fast on the kept
        tombstones (dropping them would let a live straggler re-open the
        round and block out its full timeout alone). The gang supervisor
        calls this when a member dies so surviving members never sit out
        the full round timeout on a peer that will never arrive (ISSUE 4:
        "interrupt the collective, no wedged barrier"). COMPLETED rounds
        (event set with payload refs present — every contribution arrived,
        laggards just haven't collected yet) are left alone: aborting one
        would hand some members the real result and others None, desyncing
        a group with no member dead. Aborted p2p tombstones have no served
        counter and persist until the _MAX_ROUNDS eviction — after a
        non-destructive abort, destroy/re-create the group before reusing
        p2p keys. Returns the number of rounds aborted."""
        with self._lock:
            n = 0
            for r in self._rounds.values():
                if not r.get("aborted") and not (
                    r["event"].is_set() and r["refs"]
                ):
                    r["aborted"] = True
                    r["refs"].clear()
                    r["event"].set()
                    n += 1
            if len(self._rounds) > self._MAX_ROUNDS:
                excess = len(self._rounds) - self._MAX_ROUNDS
                for key in [
                    k for k, r in self._rounds.items() if r.get("aborted")
                ][:excess]:
                    self._rounds.pop(key)
            return n

    # ------------------------------------------------------------------ p2p
    def put_p2p(self, key: str, ref) -> bool:
        with self._lock:
            r = self._round(key)
            if r.get("aborted"):
                return False  # tombstoned incarnation: don't park a payload
            r["refs"][0] = ref
            r["event"].set()
        return True

    def await_p2p(self, key: str, timeout: float = 300.0):
        with self._lock:
            r = self._round(key)
        if not r["event"].wait(timeout):
            return None
        with self._lock:
            if r.get("aborted"):
                # abort_rounds cleared refs and set the event to release
                # this waiter; KEEP the tombstone (same rule as
                # contribute_and_await) so a straggler peer fails fast
                # instead of re-opening the round.
                return None
            self._rounds.pop(key, None)
            return r["refs"][0]


# Back-compat alias (round-1 name).
GroupInfo = GroupRendezvous

_LOCAL = threading.local()


def _info_actor(group_name: str, world_size: Optional[int] = None, create: bool = False):
    from ..core import api

    name = f"__collective_{group_name}"
    handle = api.get_actor_or_none(name)
    if handle is None and create:
        remote_cls = api.remote(GroupRendezvous)
        try:
            handle = remote_cls.options(
                name=name,
                lifetime="detached",
                # Members BLOCK inside contribute_and_await; every member
                # needs a thread, with headroom for bookkeeping calls.
                max_concurrency=(world_size or 16) * 2 + 4,
            ).remote(world_size)
        except ValueError:
            handle = api.get_actor(name)
    if handle is None:
        raise ValueError(f"Collective group '{group_name}' does not exist")
    return handle


def _ctx() -> dict:
    if not hasattr(_LOCAL, "groups"):
        _LOCAL.groups = {}
    return _LOCAL.groups


_VALID_BACKENDS = {"host", "gloo", "xla", "nccl"}


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = Backend.HOST,
    group_name: str = "default",
):
    """Called by each member (inside its actor/task) to join a group."""
    from ..core import api

    b = str(backend).lower()
    if b not in _VALID_BACKENDS:
        raise ValueError(f"Unknown collective backend {backend!r}; valid: {_VALID_BACKENDS}")
    if b in ("xla", "nccl"):
        import warnings

        warnings.warn(
            "Device-plane collectives on TPU compile into jit programs "
            "(ray_tpu.collective.ops.* under shard_map/pjit); group "
            f"'{group_name}' will use the host plane for out-of-jit arrays.",
            stacklevel=2,
        )
    info = _info_actor(group_name, world_size, create=True)
    api.get(info.join.remote(rank))
    if not api.get(info.await_ready.remote(60.0)):
        raise TimeoutError(f"Group {group_name} rendezvous timed out")
    _ctx()[group_name] = {"info": info, "rank": rank, "world_size": world_size, "seq": 0}


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = Backend.HOST,
    group_name: str = "default",
):
    """Declarative variant (reference `collective.py:151`): the DRIVER
    assigns ranks to actor handles up front; member processes auto-join on
    their first collective call (rank resolved from their actor id)."""
    from ..core import api

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    info = _info_actor(group_name, world_size, create=True)
    mapping = {a._id.hex(): r for a, r in zip(actors, ranks)}
    api.get(info.assign_ranks.remote(mapping))
    return True


def abort_collective_group(group_name: str = "default", timeout: float = 5.0) -> bool:
    """Interrupt every in-flight round of a group WITHOUT destroying it:
    members blocked in a collective get a prompt TimeoutError instead of
    waiting out the full round timeout on a dead peer. Driver-callable
    (no membership required). Returns False when the group doesn't exist
    or the abort didn't land within `timeout`."""
    from ..core import api

    try:
        handle = api.get_actor_or_none(f"__collective_{group_name}")
        if handle is None:
            return False
        api.get(handle.abort_rounds.remote(), timeout=timeout)
        return True
    except Exception:  # noqa: BLE001 — rendezvous actor itself may be dying
        return False


def destroy_collective_group(group_name: str = "default"):
    from ..core import api

    try:
        handle = api.get_actor_or_none(f"__collective_{group_name}")
        if handle is not None:
            api.kill(handle)
    finally:
        _ctx().pop(group_name, None)


def _group(group_name: str) -> dict:
    """Resolve this process's membership — explicit init or driver-assigned
    rank (create_collective_group) discovered from the runtime actor id."""
    g = _ctx().get(group_name)
    if g is not None:
        return g
    from ..core import api
    from ..core.runtime_context import get_runtime_context

    actor_hex = get_runtime_context().get_actor_id()
    if actor_hex:
        info = _info_actor(group_name)
        rank = api.get(info.assigned_rank.remote(actor_hex))
        if rank >= 0:
            api.get(info.join.remote(rank))
            world = api.get(info.get_world_size.remote())
            g = {"info": info, "rank": rank, "world_size": world, "seq": 0}
            _ctx()[group_name] = g
            return g
    raise RuntimeError(
        f"init_collective_group('{group_name}') must be called in this process "
        "first (or the driver must assign this actor a rank via "
        "create_collective_group)"
    )


def get_rank(group_name: str = "default") -> int:
    g = _ctx().get(group_name)
    return g["rank"] if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _ctx().get(group_name)
    return g["world_size"] if g else -1


def _exchange(g: dict, tag: str, value) -> Dict[int, "object"]:
    """One rendezvous round: put `value` in the store, swap refs via the
    group actor (blocking — no polling), return {rank: ref}."""
    from ..core import api

    g["seq"] += 1
    key = f"{tag}:{g['seq']}"
    ref = api.put(value)
    # Wrapped in a list: TOP-LEVEL ObjectRef args are resolved to values
    # before actor execution (reference semantics); nested refs travel as
    # refs — which is the whole point of the rendezvous-only design.
    wrapped = api.get(g["info"].contribute_and_await.remote(key, g["rank"], [ref]))
    if wrapped is None:
        raise TimeoutError(
            f"collective round {key} timed out/aborted — the group is "
            f"desynchronized; destroy_collective_group() and re-init"
        )
    return {r: w[0] for r, w in wrapped.items()}


def _reduce(vals: List[np.ndarray], op: str) -> np.ndarray:
    if op == "sum":
        out = np.array(vals[0], copy=True)
        for v in vals[1:]:
            out = out + v
        return out
    if op == "max":
        return np.maximum.reduce(vals)
    if op == "min":
        return np.minimum.reduce(vals)
    if op == "prod":
        return np.multiply.reduce(vals)
    raise ValueError(f"unknown op {op}")


_RS_AG_MIN_WORLD = 5
_RS_AG_MIN_SIZE = 4096  # elements; below this the chunking overhead dominates


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """Host-plane allreduce (reference `collective.py:258`). Tensors ride
    the object store peer-to-peer; for world ≥ 5 and non-trivial sizes the
    bandwidth-optimal reduce-scatter + allgather runs (per-member traffic
    ~3×size; the naive gather is world×size). For on-device tensors inside
    jit use `ops.psum`/`allreduce_jit`."""
    from ..core import api

    g = _group(group_name)
    x = np.asarray(tensor)
    if g["world_size"] >= _RS_AG_MIN_WORLD and x.size >= _RS_AG_MIN_SIZE:
        return _allreduce_rs_ag(g, x, op)
    refs = _exchange(g, f"ar-{op}", x)
    vals = [np.asarray(api.get(refs[r])) for r in sorted(refs)]
    return _reduce(vals, op)


def _allreduce_rs_ag(g: dict, x: np.ndarray, op: str) -> np.ndarray:
    """Reduce-scatter + allgather over flat chunks (ring-equivalent traffic)."""
    from ..core import api

    world, rank = g["world_size"], g["rank"]
    flat = x.reshape(-1)
    pad = (-len(flat)) % world
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    chunks = flat.reshape(world, -1)
    # Round 1: publish per-chunk objects; fetch every member's chunk `rank`.
    my_chunk_refs = [api.put(np.array(chunks[c], copy=True)) for c in range(world)]
    lists = _exchange(g, f"rs-{op}", my_chunk_refs)
    # Each exchanged value is itself a (tiny) list-of-refs object; fetch the
    # manifest, then only chunk `rank` of every member's payload.
    manifests = {m: api.get(lists[m]) for m in lists}
    mine = [np.asarray(api.get(manifests[m][rank])) for m in sorted(manifests)]
    reduced = _reduce(mine, op)
    # Round 2: publish the reduced chunk; gather all reduced chunks.
    out_refs = _exchange(g, f"ag-{op}", reduced)
    parts = [np.asarray(api.get(out_refs[m])) for m in sorted(out_refs)]
    full = np.concatenate(parts)
    if pad:
        full = full[: len(full) - pad]
    return full.reshape(x.shape)


def allgather(tensor, group_name: str = "default"):
    from ..core import api

    g = _group(group_name)
    refs = _exchange(g, "gather", np.asarray(tensor))
    return [np.array(api.get(refs[r]), copy=True) for r in sorted(refs)]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Root publishes ONE object; every member reads it from the store
    (zero-copy locally, one transfer per remote node) — the rendezvous actor
    sees only the ref."""
    from ..core import api

    g = _group(group_name)
    x = np.asarray(tensor) if g["rank"] == src_rank else None
    refs = _exchange(g, "bcast", x)
    return np.array(api.get(refs[src_rank]), copy=True)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    """Each member gets chunk `rank` of the axis-0-split reduction. Large
    tensors use the chunked manifest (members fetch ONLY their chunk from
    each peer — per-member traffic ~2×size instead of world×size)."""
    from ..core import api

    g = _group(group_name)
    world, rank = g["world_size"], g["rank"]
    x = np.asarray(tensor)
    if world >= _RS_AG_MIN_WORLD and x.size >= _RS_AG_MIN_SIZE:
        chunks = np.array_split(x, world, axis=0)
        my_chunk_refs = [api.put(np.array(c, copy=True)) for c in chunks]
        lists = _exchange(g, f"rsc-{op}", my_chunk_refs)
        manifests = {m: api.get(lists[m]) for m in lists}
        mine = [np.asarray(api.get(manifests[m][rank])) for m in sorted(manifests)]
        return _reduce(mine, op)
    refs = _exchange(g, f"rsc-{op}", x)
    vals = [np.asarray(api.get(refs[r])) for r in sorted(refs)]
    total = _reduce(vals, op)
    return np.array_split(total, world, axis=0)[rank]


def reduce_scatter_flat(vec, group_name: str = "default", op: str = "sum"):
    """ZeRO gradient exchange, host plane: elementwise-reduce a FLAT 1-D
    vector across the group and return THIS rank's np.array_split chunk of
    the result (the exact chunking `ops.zero_shard_bounds` describes, which
    is also the elastic checkpoint's axis-0 reshard rule — so optimizer
    shards, wire chunks, and checkpoint shards all agree for any world
    size). Per-member traffic ~2x size via the per-chunk manifest (each
    member fetches only its chunk from every peer); world_size 1 degrades
    to a local reduce. Reduction order is sorted-rank, so every member
    computes bit-identical results."""
    from ..core import api

    g = _group(group_name)
    world, rank = g["world_size"], g["rank"]
    x = np.asarray(vec).reshape(-1)
    if world == 1:
        return np.array(x, copy=True)
    chunks = np.array_split(x, world)
    my_chunk_refs = [api.put(np.array(c, copy=True)) for c in chunks]
    lists = _exchange(g, f"rsf-{op}", my_chunk_refs)
    manifests = {m: api.get(lists[m]) for m in lists}
    mine = [np.asarray(api.get(manifests[m][rank])) for m in sorted(manifests)]
    return _reduce(mine, op)


def all_gather_flat(chunk, group_name: str = "default"):
    """Inverse half of the ZeRO update: concatenate every rank's flat chunk
    in rank order (np.array_split layout) back into the full vector."""
    from ..core import api

    g = _group(group_name)
    if g["world_size"] == 1:
        return np.array(np.asarray(chunk).reshape(-1), copy=True)
    refs = _exchange(g, "agf", np.asarray(chunk).reshape(-1))
    return np.concatenate(
        [np.asarray(api.get(refs[r])).reshape(-1) for r in sorted(refs)]
    )


def barrier(group_name: str = "default"):
    _exchange(_group(group_name), "barrier", None)


def _p2p_key(g: dict, src: int, dst: int) -> str:
    # Both endpoints count their mutual transfers, so the pair's keys line up
    # regardless of what other collectives either side ran in between.
    p2p = g.setdefault("p2p", {})
    p2p[(src, dst)] = p2p.get((src, dst), 0) + 1
    return f"p2p:{src}->{dst}:{p2p[(src, dst)]}"


def send(tensor, dst_rank: int, group_name: str = "default"):
    """Point-to-point: the ref rides the rendezvous actor, the payload rides
    the store."""
    from ..core import api

    g = _group(group_name)
    key = _p2p_key(g, g["rank"], dst_rank)
    ref = api.put(np.asarray(tensor))
    ok = api.get(g["info"].put_p2p.remote(key, [ref]))  # nested: stays a ref
    if not ok:
        # Tombstoned round: the group was aborted while the receiver
        # waited — the payload was refused, and pretending delivery
        # succeeded would desync sender and receiver.
        raise TimeoutError(
            f"send to rank {dst_rank} aborted (group {group_name!r} aborted)"
        )


def recv(src_rank: int, group_name: str = "default"):
    from ..core import api

    g = _group(group_name)
    key = _p2p_key(g, src_rank, g["rank"])
    wrapped = api.get(g["info"].await_p2p.remote(key, 300.0))
    if wrapped is None:
        raise TimeoutError("recv timed out")
    return np.array(api.get(wrapped[0]), copy=True)


__all__ = [
    "Backend",
    "init_collective_group",
    "create_collective_group",
    "abort_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allgather",
    "broadcast",
    "reducescatter",
    "reduce_scatter_flat",
    "all_gather_flat",
    "barrier",
    "send",
    "recv",
    # in-jit plane
    "ops",
    "psum",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "ppermute",
    "allreduce_jit",
    "barrier_jit",
]
