"""In-jit collectives — the ICI plane.

The reference's NCCL calls (`nccl_collective_group.py:allreduce` etc.) map on
TPU to XLA collective HLOs compiled into the program. These wrappers add
nothing at runtime — they exist so framework code reads at the same level of
intent as the reference API, and so the axis-name conventions of
`ray_tpu.parallel.mesh.AXIS_ORDER` are applied consistently.

All functions must be called under `shard_map`/`pjit` with bound axis names.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str]]


def psum(x, axis: AxisName):
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: AxisName):
    return jax.lax.pmean(x, axis_name=axis)

def pmax(x, axis: AxisName):
    return jax.lax.pmax(x, axis_name=axis)


def pmin(x, axis: AxisName):
    return jax.lax.pmin(x, axis_name=axis)


def allreduce_jit(x, axis: AxisName, op: str = "sum"):
    return {"sum": psum, "mean": pmean, "max": pmax, "min": pmin}[op](x, axis)


def all_gather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    return jax.lax.all_gather(x, axis_name=axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0, op: str = "sum"):
    if op not in ("sum", "mean"):
        raise NotImplementedError("reduce_scatter supports sum/mean on TPU ICI")
    out = jax.lax.psum_scatter(
        x, axis_name=axis, scatter_dimension=scatter_axis, tiled=True
    )
    if op == "mean":
        out = out / axis_size(axis)
    return out


def all_to_all(
    x,
    axis: AxisName,
    *,
    split_axis: int,
    concat_axis: int,
    tiled: bool = True,
):
    """Ulysses-style head/sequence exchange rides this (`SURVEY.md §5`)."""
    return jax.lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def ppermute(x, axis: AxisName, perm: Sequence[tuple]):
    """Neighbor exchange — the ring-attention building block."""
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def ring_shift(x, axis: str, shift: int = 1):
    """Send x to (rank+shift) mod n along `axis`; returns the received block."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: AxisName):
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    # jax.lax.axis_size only exists in newer JAX; psum of a Python constant
    # over a named axis constant-folds to the axis size at trace time, so
    # the result stays a static int (ppermute tables need it).
    return jax.lax.psum(1, axis)


def barrier_jit(axis: AxisName):
    """Sync point inside jit: a zero-sized psum forces a collective."""
    return jax.lax.psum(jnp.zeros((), jnp.int32), axis_name=axis)


def unreplicate(tree):
    """Take the first element along a leading device axis (host-side)."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)


# ------------------------------------------------------- ZeRO flat sharding
# Helpers for the cross-replica sharded weight update (arXiv 2004.13336):
# the optimizer works in ONE flat f32 parameter space, each data-parallel
# replica owning a contiguous chunk of it. Chunk boundaries use
# np.array_split sizing — the SAME partitioning rule the elastic
# checkpoint's axis-0 reshard applies (train/elastic/ckpt.py), so a shard
# saved at dp=4 restores as exactly rank r's runtime chunk at dp=2 with no
# re-padding. Any flat length works for any world size (no divisibility
# constraint; elementwise optimizers don't care about uneven chunks).
# The HOST-plane collectives these compose with (reduce_scatter_flat /
# all_gather_flat, object-store rendezvous between gang actors) live in
# ray_tpu.collective; the in-jit reduce_scatter/all_gather above are their
# ICI analogs.


def zero_shard_bounds(n: int, world: int, rank: int) -> "tuple[int, int]":
    """[start, end) of rank's chunk of a flat length-n vector under
    np.array_split sizing (first n % world chunks get one extra element)."""
    q, rem = divmod(int(n), int(world))
    start = rank * q + min(rank, rem)
    return start, start + q + (1 if rank < rem else 0)


def zero_flatten(tree):
    """Pytree -> (flat f32 1-D np.ndarray, spec). `spec` (a list of
    (shape, dtype) in tree_flatten leaf order + the treedef) round-trips
    through zero_unflatten. Master/optimizer math runs in f32 regardless of
    the working dtype — the f32-master half of the ZeRO recipe."""
    import numpy as np
    from jax import tree_util

    leaves, treedef = tree_util.tree_flatten(tree)
    spec = {
        "treedef": treedef,
        "leaves": [(tuple(np.shape(x)), np.asarray(x).dtype.str) for x in leaves],
    }
    if not leaves:
        return np.zeros((0,), np.float32), spec
    flat = np.concatenate(
        [np.asarray(x, dtype=np.float32).reshape(-1) for x in leaves]
    )
    return flat, spec


def zero_unflatten(flat, spec, cast: bool = True):
    """Inverse of zero_flatten. With cast=True each leaf is cast back to its
    recorded dtype (the working-precision tree); cast=False keeps f32."""
    import numpy as np
    from jax import tree_util

    out, pos = [], 0
    for shape, dtype_str in spec["leaves"]:
        n = int(np.prod(shape)) if shape else 1
        leaf = np.asarray(flat[pos : pos + n]).reshape(shape)
        if cast:
            leaf = leaf.astype(np.dtype(dtype_str))
        out.append(leaf)
        pos += n
    return tree_util.tree_unflatten(spec["treedef"], out)
