"""In-jit collectives — the ICI plane.

The reference's NCCL calls (`nccl_collective_group.py:allreduce` etc.) map on
TPU to XLA collective HLOs compiled into the program. These wrappers add
nothing at runtime — they exist so framework code reads at the same level of
intent as the reference API, and so the axis-name conventions of
`ray_tpu.parallel.mesh.AXIS_ORDER` are applied consistently.

All functions must be called under `shard_map`/`pjit` with bound axis names.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str]]


def psum(x, axis: AxisName):
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: AxisName):
    return jax.lax.pmean(x, axis_name=axis)

def pmax(x, axis: AxisName):
    return jax.lax.pmax(x, axis_name=axis)


def pmin(x, axis: AxisName):
    return jax.lax.pmin(x, axis_name=axis)


def allreduce_jit(x, axis: AxisName, op: str = "sum"):
    return {"sum": psum, "mean": pmean, "max": pmax, "min": pmin}[op](x, axis)


def all_gather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    return jax.lax.all_gather(x, axis_name=axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0, op: str = "sum"):
    if op != "sum":
        raise NotImplementedError("reduce_scatter supports sum on TPU ICI")
    return jax.lax.psum_scatter(
        x, axis_name=axis, scatter_dimension=scatter_axis, tiled=True
    )


def all_to_all(
    x,
    axis: AxisName,
    *,
    split_axis: int,
    concat_axis: int,
    tiled: bool = True,
):
    """Ulysses-style head/sequence exchange rides this (`SURVEY.md §5`)."""
    return jax.lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def ppermute(x, axis: AxisName, perm: Sequence[tuple]):
    """Neighbor exchange — the ring-attention building block."""
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def ring_shift(x, axis: str, shift: int = 1):
    """Send x to (rank+shift) mod n along `axis`; returns the received block."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: AxisName):
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    # jax.lax.axis_size only exists in newer JAX; psum of a Python constant
    # over a named axis constant-folds to the axis size at trace time, so
    # the result stays a static int (ppermute tables need it).
    return jax.lax.psum(1, axis)


def barrier_jit(axis: AxisName):
    """Sync point inside jit: a zero-sized psum forces a collective."""
    return jax.lax.psum(jnp.zeros((), jnp.int32), axis_name=axis)


def unreplicate(tree):
    """Take the first element along a leading device axis (host-side)."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)
