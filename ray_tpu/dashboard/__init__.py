"""Dashboard — HTTP observability UI + JSON API over controller state.

Reference analog: `dashboard/` (47k LoC: aiohttp head + per-node agents + a
React/TS frontend). Redesign: controller state already lives in one process,
so the dashboard is an asyncio HTTP server inside it — JSON endpoints backed
directly by the controller's state-API handlers plus one self-contained HTML
page (no build step, no node_modules). Prometheus stays on its own port
(`/metrics`); the page links to it.

Endpoints:
    GET /                  HTML overview (auto-refreshing tables)
    GET /api/cluster       resource totals/availability + counts
    GET /api/nodes         node directory
    GET /api/actors        actor directory
    GET /api/tasks         pending/running tasks
    GET /api/objects       object index (?limit=N)
    GET /api/workers       worker pool
    GET /api/jobs          submitted jobs
    GET /api/pgs           placement groups
    GET /api/events        recent timeline events (?limit=N)
    GET /api/traces        recent request traces (summary rows, ?limit=N)
    GET /api/traces?trace_id=ID  one trace's full span forest
    GET /api/flight        merged flight-recorder payload (lanes, pipeline
                           bubble report, ONE Perfetto chrome-trace;
                           ?trace_id=ID restricts the chrome-trace)
    GET /api/logs?worker_id=ID   tail of one worker's log
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse
from typing import Optional

MAX_REQUEST_LINE = 8192


class DashboardServer:
    def __init__(self, controller):
        self.controller = controller
        self.port = 0
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self, port: int = 0):
        from ..core import config as rt_config

        bind = rt_config.get("bind_address") or rt_config.get("node_ip")
        self._server = await asyncio.start_server(
            self._on_connection, host=bind, port=port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    def close(self):
        if self._server is not None:
            self._server.close()

    # ------------------------------------------------------------- plumbing
    async def _on_connection(self, reader, writer):
        try:
            line = await asyncio.wait_for(reader.readline(), 5)
            if len(line) > MAX_REQUEST_LINE:
                return
            for _ in range(100):  # drain request headers (bounded)
                h = await asyncio.wait_for(reader.readline(), 5)
                if h in (b"\r\n", b"\n", b""):
                    break
            parts = line.split(b" ")
            target = parts[1].decode() if len(parts) > 1 else "/"
            parsed = urllib.parse.urlsplit(target)
            query = dict(urllib.parse.parse_qsl(parsed.query))
            status, ctype, body = await self._route(parsed.path, query)
            writer.write(
                f"HTTP/1.0 {status}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 — a broken client must not hurt the controller
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _route(self, path: str, query: dict):
        c = self.controller
        if path in ("/", "/index.html"):
            return "200 OK", "text/html; charset=utf-8", _INDEX_HTML
        if not path.startswith("/api/"):
            return "404 Not Found", "text/plain", b"not found"
        try:
            name = path[len("/api/"):]
            if name == "cluster":
                data = await self._cluster_summary()
            elif name == "nodes":
                data = await c.h_nodes(None, {}, {})
            elif name == "actors":
                data = await c.h_list_actors(None, {}, {})
            elif name == "tasks":
                data = await c.h_list_tasks(None, {}, {})
            elif name == "objects":
                data = await c.h_list_objects(
                    None, {}, {"limit": int(query.get("limit", 200))}
                )
            elif name == "workers":
                data = await c.h_list_workers(None, {}, {})
            elif name == "jobs":
                data = await c.h_list_jobs(None, {}, {})
            elif name == "pgs":
                data = {
                    "placement_groups": [
                        {
                            "pg_id": k,
                            "name": v.get("name", ""),
                            "strategy": v["strategy"],
                            "ready": v["ready"],
                            "bundles": v["bundles"],
                            "bundle_nodes": v["bundle_nodes"],
                        }
                        for k, v in c.pgs.items()
                    ]
                }
            elif name == "events":
                limit = max(0, int(query.get("limit", 100)))
                data = {"events": list(c.timeline[-limit:]) if limit else []}
            elif name == "traces":
                from ..util import tracing

                # Same bounded window as state_summary (what the CLI and
                # api.timeline() see): keeps the two surfaces consistent and
                # caps the forest assembly this does on the controller's
                # event loop (the full timeline can hold 100k events).
                # ONE export path shared with `ray-tpu trace`
                # (tracing.trace_payload): CLI and HTTP cannot drift.
                events = list(c.timeline[-10000:])
                trace_id = query.get("trace_id")
                if trace_id:
                    t = tracing.trace_payload(events, trace_id=trace_id)["trace"]
                    if t is None:
                        return (
                            "404 Not Found",
                            "application/json",
                            json.dumps({"error": f"unknown trace {trace_id}"}).encode(),
                        )
                    data = t
                else:
                    limit = max(1, int(query.get("limit", 50)))
                    data = tracing.trace_payload(events, limit=limit)
            elif name == "flight":
                from ..util import flight

                # Pull-on-demand: poke every live worker to flush its span
                # ring, give the task_events piggybacks a beat to land, then
                # build the merged payload — the same builder as
                # `ray-tpu flight` (flight.flight_payload), so the two
                # surfaces emit identical output for the same timeline.
                await c.h_flight_pull(None, {}, {})
                await asyncio.sleep(0.25)
                data = flight.flight_payload(
                    list(c.timeline[-10000:]), trace_id=query.get("trace_id")
                )
            elif name == "logs":
                wid = query.get("worker_id", "")
                if not wid:
                    return (
                        "400 Bad Request",
                        "application/json",
                        b'{"error": "worker_id query parameter required"}',
                    )
                # Real tail: learn the end offset first, then read only the
                # last chunk (a long-lived worker log can be GBs).
                tail_bytes = min(int(query.get("bytes", 65536)), 1 << 20)
                head = await c.h_tail_logs(
                    None, {}, {"worker_id": wid, "init": True}
                )
                end = head.get("logs", {}).get(wid, {}).get("offset", 0)
                got = await c.h_tail_logs(
                    None, {},
                    {"worker_id": wid,
                     "cursors": {wid: max(0, end - tail_bytes)}},
                )
                data = {"worker_id": wid,
                        "log": got.get("logs", {}).get(wid, {}).get("data", "")}
            else:
                return "404 Not Found", "text/plain", b"unknown api"
            body = json.dumps({"ts": time.time(), **data}, default=str).encode()
            return "200 OK", "application/json", body
        except Exception as e:  # noqa: BLE001
            return (
                "500 Internal Server Error",
                "application/json",
                json.dumps({"error": repr(e)}).encode(),
            )

    async def _cluster_summary(self) -> dict:
        c = self.controller
        totals = await c.h_cluster_resources(None, {}, {})
        summary = await c.h_state_summary(None, {}, {"counts_only": True})
        return {
            "resources": totals,
            "summary": summary,
            "metrics_url": f"http://127.0.0.1:{c.metrics_port}/metrics",
            "session_dir": c.session_dir,
            "nodes_alive": sum(1 for n in c.nodes.values() if n.alive),
        }


_INDEX_HTML = b"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
  body { font: 13px/1.5 system-ui, sans-serif; margin: 24px; color: #1a1a22; }
  h1 { font-size: 18px; } h2 { font-size: 14px; margin: 20px 0 6px; }
  table { border-collapse: collapse; min-width: 520px; }
  th, td { border: 1px solid #d5d5de; padding: 3px 9px; text-align: left; }
  th { background: #f2f2f7; font-weight: 600; }
  .tiles { display: flex; gap: 12px; flex-wrap: wrap; margin: 12px 0; }
  .tile { border: 1px solid #d5d5de; border-radius: 6px; padding: 8px 14px; }
  .tile b { display: block; font-size: 20px; }
  .muted { color: #6a6a75; } a { color: #2440b3; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div class="tiles" id="tiles"></div>
<p class="muted">auto-refresh 2s &middot; <a id="mlink" href="#">prometheus /metrics</a></p>
<h2>Nodes</h2><div id="nodes"></div>
<h2>Actors</h2><div id="actors"></div>
<h2>Tasks</h2><div id="tasks"></div>
<h2>Workers</h2><div id="workers"></div>
<h2>Placement groups</h2><div id="pgs"></div>
<h2>Jobs</h2><div id="jobs"></div>
<h2>Traces</h2><div id="traces"></div>
<h2>Recent events</h2><div id="events"></div>
<script>
function esc(s) {
  return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;').replace(/>/g,'&gt;');
}
function table(rows, cols) {
  if (!rows || !rows.length) return '<p class="muted">none</p>';
  let h = '<table><tr>' + cols.map(c => '<th>'+esc(c)+'</th>').join('') + '</tr>';
  for (const r of rows)
    h += '<tr>' + cols.map(c => '<td>'+esc(JSON.stringify(r[c] ?? ''))+'</td>').join('') + '</tr>';
  return h + '</table>';
}
async function j(p) { return (await fetch(p)).json(); }
async function refresh() {
  try {
    const cl = await j('/api/cluster');
    document.getElementById('mlink').href = cl.metrics_url;
    const s = cl.summary, res = cl.resources;
    document.getElementById('tiles').innerHTML =
      ['nodes_alive','num_workers','pending_tasks','running_tasks','objects']
        .map(k => '<div class="tile"><b>'+esc(k==='nodes_alive'?cl[k]:s[k])+'</b>'+esc(k.replace(/_/g,' '))+'</div>').join('') +
      '<div class="tile"><b>'+esc(JSON.stringify(res.total ?? res))+'</b>resources</div>';
    const [n,a,t,w,p,jb,e,tr] = await Promise.all([
      j('/api/nodes'), j('/api/actors'), j('/api/tasks'),
      j('/api/workers'), j('/api/pgs'), j('/api/jobs'), j('/api/events'),
      j('/api/traces?limit=15')]);
    document.getElementById('nodes').innerHTML =
      table(n.nodes, ['NodeID','Alive','Resources','Available']);
    document.getElementById('actors').innerHTML =
      table(a.actors, ['actor_id','name','state','node_id','restarts','pending_calls']);
    document.getElementById('tasks').innerHTML =
      table(t.tasks, ['task_id','name','state','node_id','required_resources']);
    document.getElementById('workers').innerHTML =
      table(w.workers, ['worker_id','state','pid','node_id','current_task','actor']);
    document.getElementById('pgs').innerHTML =
      table(p.placement_groups, ['pg_id','name','strategy','ready','bundle_nodes']);
    document.getElementById('jobs').innerHTML =
      table(jb.jobs, ['job_id','status','entrypoint']);
    document.getElementById('traces').innerHTML =
      table(tr.traces, ['trace_id','name','start','duration','n_tasks','n_spans']);
    document.getElementById('events').innerHTML =
      table((e.events||[]).slice().reverse().slice(0,25), ['ts','event','task','node']);
  } catch (err) { console.error(err); }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""
