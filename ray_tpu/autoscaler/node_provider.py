"""Node providers — how the autoscaler actually adds/removes machines.

Reference analog: `python/ray/autoscaler/node_provider.py` `NodeProvider`
ABC with cloud implementations (aws/gcp/...) and the hermetic
`FakeMultiNodeProvider` (`_private/fake_multi_node/node_provider.py`) that
"launches nodes" as local processes — the pattern all autoscaler CI uses.

The TPU-cloud provider (GKE / TPU-VM REST calls) is a deliberate stub here:
this environment has zero egress, so only its interface is laid down.
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, List, Optional

TAG_NODE_KIND = "ray_tpu-node-kind"  # "head" | "worker"
TAG_NODE_TYPE = "ray_tpu-user-node-type"
TAG_NODE_STATUS = "ray_tpu-node-status"

NODE_KIND_HEAD = "head"
NODE_KIND_WORKER = "worker"
STATUS_UP_TO_DATE = "up-to-date"
STATUS_TERMINATED = "terminated"


class NodeProvider:
    """Minimal provider contract the autoscaler needs.

    Node ids returned here are the same ids the node agents register with the
    controller under, so the autoscaler can join provider state with
    `load_metrics` node reports without an ip-mapping layer (the reference
    joins on internal_ip)."""

    def __init__(self, provider_config: dict, cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def create_node(
        self, node_config: dict, tags: Dict[str, str], count: int
    ) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches "nodes" as local `node_agent` processes against a live
    controller — autoscaler logic is testable with no cloud at all
    (reference: `fake_multi_node/node_provider.py`)."""

    def __init__(self, provider_config: dict, cluster_name: str = "fake"):
        super().__init__(provider_config, cluster_name)
        self.address: str = provider_config["address"]
        self.session_dir: str = provider_config["session_dir"]
        self._lock = threading.Lock()
        self._counter = 0
        # node_id -> {proc, tags}
        self._nodes: Dict[str, dict] = {}

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for nid, info in self._nodes.items():
                if info["proc"].poll() is not None:
                    continue
                tags = info["tags"]
                if all(tags.get(k) == v for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            info = self._nodes.get(node_id)
            return info is not None and info["proc"].poll() is None

    def create_node(
        self, node_config: dict, tags: Dict[str, str], count: int
    ) -> List[str]:
        from ..cluster_utils import launch_node_agent

        created = []
        for _ in range(count):
            with self._lock:
                self._counter += 1
                node_id = f"fake-{self.cluster_name}-{self._counter}"
            proc = launch_node_agent(
                self.address,
                self.session_dir,
                node_id,
                {k: float(v) for k, v in node_config.get("resources", {}).items()},
                node_config.get("object_store_memory"),
            )
            with self._lock:
                self._nodes[node_id] = {
                    "proc": proc,
                    "tags": {**tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE},
                }
            created.append(node_id)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return
            info["tags"][TAG_NODE_STATUS] = STATUS_TERMINATED
            proc: subprocess.Popen = info["proc"]
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def shutdown(self):
        for nid in list(self._nodes):
            self.terminate_node(nid)


class TPUVMNodeProvider(NodeProvider):
    """Interface stub for real TPU-VM / GKE provisioning (requires cloud
    APIs — unavailable here; reference cloud analog:
    `autoscaler/_private/gcp/node_provider.py`). Raises on use."""

    def _unavailable(self):
        raise RuntimeError(
            "TPUVMNodeProvider needs GCP API access; use FakeMultiNodeProvider "
            "for local clusters or implement create_node via the TPU VM REST API."
        )

    def non_terminated_nodes(self, tag_filters):
        self._unavailable()

    def node_tags(self, node_id):
        self._unavailable()

    def is_running(self, node_id):
        self._unavailable()

    def create_node(self, node_config, tags, count):
        self._unavailable()

    def terminate_node(self, node_id):
        self._unavailable()
