"""Bin-packing of unmet resource demand onto node types.

Reference analog: `python/ray/autoscaler/_private/resource_demand_scheduler.py`
— first-fit-decreasing over existing capacity, then over planned new nodes,
choosing node types that fit; bounded by per-type and global max_workers.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

EPS = 1e-9


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + EPS >= v for k, v in demand.items())


def _take(avail: Dict[str, float], demand: Dict[str, float]):
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def _size(demand: Dict[str, float]) -> float:
    # TPU demand dominates the ordering so accelerator bundles pack first.
    return demand.get("TPU", 0.0) * 1e6 + sum(demand.values())


def pack_feasible(
    capacities: List[Dict[str, float]], demands: List[Dict[str, float]]
) -> bool:
    """First-fit-decreasing check: do all demand bundles pack into the given
    capacities? Used for idle-termination safety against the explicit floor."""
    scratch = [dict(c) for c in capacities]
    for demand in sorted(demands, key=_size, reverse=True):
        for cap in scratch:
            if _fits(cap, demand):
                _take(cap, demand)
                break
        else:
            return False
    return True


def get_nodes_to_launch(
    node_types: Dict[str, dict],
    counts_by_type: Dict[str, int],
    existing_avail,
    demands: List[Dict[str, float]],
    explicit_demands: List[Dict[str, float]],
    existing_totals=None,
    max_workers: int = 64,
    strict_spread_groups: List[dict] = (),
) -> Dict[str, int]:
    """Decide how many new nodes of each type to launch.

    `node_types`: {type_name: {"resources": {...}, "min_workers": int,
    "max_workers": int}}. `counts_by_type`: live worker-node counts.
    `existing_avail`: available resources of live nodes — a
    {node_id: resources} mapping, or a bare list when node identity does not
    matter (demands consume these first). `explicit_demands` are matched
    against whole-node *totals* (capacity floor semantics of
    `request_resources`). `strict_spread_groups` entries are
    {"bundles": [...], "occupied": [node_id, ...]} — each bundle needs a
    distinct node, and nodes in `occupied` are excluded (they already host
    this PG's surviving bundles).
    """
    if not isinstance(existing_avail, dict):
        existing_avail = {f"#{i}": a for i, a in enumerate(existing_avail)}
    if existing_totals is not None and not isinstance(existing_totals, dict):
        existing_totals = {f"#{i}": t for i, t in enumerate(existing_totals)}
    to_launch: Dict[str, int] = {}
    planned: List[Tuple[str, Dict[str, float]]] = []  # (type, remaining avail)
    total_workers = sum(counts_by_type.values())

    def type_count(t: str) -> int:
        return counts_by_type.get(t, 0) + to_launch.get(t, 0)

    def can_add(t: str) -> bool:
        spec = node_types[t]
        return (
            type_count(t) < spec.get("max_workers", max_workers)
            and total_workers + sum(to_launch.values()) < max_workers
        )

    def add_node(t: str) -> Dict[str, float]:
        to_launch[t] = to_launch.get(t, 0) + 1
        avail = dict(node_types[t]["resources"])
        planned.append((t, avail))
        return avail

    # 1. min_workers floors.
    for t, spec in node_types.items():
        while type_count(t) < spec.get("min_workers", 0) and can_add(t):
            add_node(t)

    # 2. Queued-task / PG-bundle demand: first-fit-decreasing against live
    # availability, then planned nodes, then new nodes.
    by_node = {nid: dict(a) for nid, a in existing_avail.items()}
    scratch = list(by_node.values())

    # 2a. STRICT_SPREAD placement groups: every bundle in a group must land
    # on a DISTINCT capacity unit (existing node or planned node) — plain
    # packing would co-pack them and permanently under-launch. Nodes already
    # hosting the group's surviving bundles are excluded up front.
    for group in strict_spread_groups:
        if isinstance(group, dict):
            bundles = group.get("bundles", [])
            occupied = group.get("occupied", [])
        else:  # bare bundle list (tests/back-compat)
            bundles, occupied = group, []
        used_ids = {id(by_node[nid]) for nid in occupied if nid in by_node}
        for demand in sorted(bundles, key=_size, reverse=True):
            placed = False
            for avail in scratch:
                if id(avail) not in used_ids and _fits(avail, demand):
                    _take(avail, demand)
                    used_ids.add(id(avail))
                    placed = True
                    break
            if placed:
                continue
            for _, avail in planned:
                if id(avail) not in used_ids and _fits(avail, demand):
                    _take(avail, demand)
                    used_ids.add(id(avail))
                    placed = True
                    break
            if placed:
                continue
            for t in sorted(
                node_types, key=lambda t: _size(node_types[t]["resources"])
            ):
                if _fits(node_types[t]["resources"], demand) and can_add(t):
                    avail = add_node(t)
                    _take(avail, demand)
                    used_ids.add(id(avail))
                    break
    for demand in sorted(demands, key=_size, reverse=True):
        placed = False
        for avail in scratch:
            if _fits(avail, demand):
                _take(avail, demand)
                placed = True
                break
        if placed:
            continue
        for _, avail in planned:
            if _fits(avail, demand):
                _take(avail, demand)
                placed = True
                break
        if placed:
            continue
        for t in sorted(node_types, key=lambda t: _size(node_types[t]["resources"])):
            if _fits(node_types[t]["resources"], demand) and can_add(t):
                _take(add_node(t), demand)
                break

    # 3. Explicit requests are a capacity floor: pack them against node
    # *totals* (live + planned), ignoring current usage.
    source = existing_totals if existing_totals is not None else existing_avail
    totals = [dict(t) for t in source.values()]
    totals += [dict(node_types[t]["resources"]) for t, _ in planned]
    for demand in sorted(explicit_demands, key=_size, reverse=True):
        placed = False
        for cap in totals:
            if _fits(cap, demand):
                _take(cap, demand)
                placed = True
                break
        if placed:
            continue
        for t in sorted(node_types, key=lambda t: _size(node_types[t]["resources"])):
            if _fits(node_types[t]["resources"], demand) and can_add(t):
                cap = add_node(t)
                _take(cap, demand)
                totals.append(cap)
                break

    return to_launch
