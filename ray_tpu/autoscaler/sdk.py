"""Autoscaler SDK — `request_resources` (reference:
`python/ray/autoscaler/sdk/__init__.py` → GCS resource_request)."""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(
    num_cpus: Optional[int] = None,
    bundles: Optional[List[Dict[str, float]]] = None,
):
    """Pin a capacity floor the autoscaler will scale to regardless of queued
    work. Call with no arguments to clear the request."""
    from ..core import api

    demand: List[Dict[str, float]] = list(bundles or [])
    if num_cpus:
        demand.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    backend = api._global_runtime().backend
    if not hasattr(backend, "_request"):
        raise RuntimeError(
            "request_resources needs a cluster backend; "
            "init with an address (cluster mode) first."
        )
    backend._request({"type": "request_resources", "bundles": demand})
