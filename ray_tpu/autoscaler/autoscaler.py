"""StandardAutoscaler — the update loop.

Reference analog: `python/ray/autoscaler/_private/autoscaler.py`
`StandardAutoscaler.update` (:171,373) run periodically by `Monitor`
(`monitor.py:126,231`): read load metrics, terminate idle nodes, bin-pack
unmet demand into node launches.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from .load_metrics import LoadMetrics
from .node_provider import (
    NODE_KIND_WORKER,
    TAG_NODE_KIND,
    TAG_NODE_TYPE,
    NodeProvider,
)
from .resource_demand_scheduler import get_nodes_to_launch, pack_feasible as _packs

logger = logging.getLogger(__name__)

DEFAULT_CONFIG = {
    "max_workers": 8,
    "idle_timeout_s": 60.0,
    "available_node_types": {},
}


class StandardAutoscaler:
    """One `update()` = one reconcile pass. The caller owns the cadence
    (`Monitor` below, or tests calling update() directly)."""

    def __init__(self, config: dict, provider: NodeProvider, backend):
        self.config = {**DEFAULT_CONFIG, **config}
        self.provider = provider
        self.backend = backend  # ClusterBackend-compatible (._request)
        self.load_metrics = LoadMetrics()

    # ---------------------------------------------------------------- state
    def _worker_nodes_by_type(self) -> Dict[str, list]:
        by_type: Dict[str, list] = {}
        for nid in self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: NODE_KIND_WORKER}
        ):
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            by_type.setdefault(t, []).append(nid)
        return by_type

    # --------------------------------------------------------------- update
    def update(self) -> Dict[str, int]:
        """Returns {node_type: launched_count} for observability/tests."""
        raw = self.backend._request({"type": "load_metrics"})
        self.load_metrics.update(raw)

        self._terminate_idle_nodes()

        node_types: Dict[str, dict] = self.config["available_node_types"]
        by_type = self._worker_nodes_by_type()
        counts = {t: len(v) for t, v in by_type.items()}
        # Launched-but-unregistered nodes count as full pending capacity so a
        # fast second update() doesn't double-launch (reference: pending-launch
        # accounting in `resource_demand_scheduler` via `pending_launches`).
        registered = set(self.load_metrics.alive_node_avail())
        pending_caps = {
            nid: dict(node_types[t]["resources"])
            for t, nids in by_type.items()
            if t in node_types
            for nid in nids
            if nid not in registered
        }
        to_launch = get_nodes_to_launch(
            node_types=node_types,
            counts_by_type=counts,
            existing_avail={
                **self.load_metrics.alive_node_avail(),
                **{k: dict(v) for k, v in pending_caps.items()},
            },
            demands=self.load_metrics.unmet_demands(),
            explicit_demands=self.load_metrics.explicit_demands,
            existing_totals={
                **self.load_metrics.alive_node_total(),
                **{k: dict(v) for k, v in pending_caps.items()},
            },
            max_workers=self.config["max_workers"],
            strict_spread_groups=self.load_metrics.strict_spread_groups,
        )
        for t, count in to_launch.items():
            logger.info("autoscaler: launching %d x %s", count, t)
            self.provider.create_node(
                node_types[t],
                {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: t},
                count,
            )
        return to_launch

    def _terminate_idle_nodes(self):
        idle = set(self.load_metrics.idle_nodes(self.config["idle_timeout_s"]))
        if not idle:
            return
        node_types = self.config["available_node_types"]
        by_type = self._worker_nodes_by_type()
        # Capacity the explicit request_resources floor still needs: a node
        # is only removable if the floor still packs into what remains
        # (otherwise terminate/relaunch would churn forever).
        remaining_totals = dict(self.load_metrics.alive_node_total())
        for t, nids in by_type.items():
            floor = node_types.get(t, {}).get("min_workers", 0)
            removable = [n for n in nids if n in idle]
            # Keep at least min_workers of this type alive.
            excess = len(nids) - floor
            for nid in removable[: max(0, excess)]:
                after = {k: v for k, v in remaining_totals.items() if k != nid}
                if not _packs(
                    list(after.values()), self.load_metrics.explicit_demands
                ):
                    continue
                logger.info("autoscaler: terminating idle node %s", nid)
                self.provider.terminate_node(nid)
                remaining_totals = after


class Monitor:
    """Background thread running `autoscaler.update()` on a fixed cadence
    (reference: `monitor.py` process on the head node)."""

    def __init__(self, autoscaler: StandardAutoscaler, update_interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("autoscaler update failed")
            self._stop.wait(self.update_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
