"""TPU-VM node provider — slice-granular scale-up against the GCE TPU API.

Reference analog: the cloud node providers under
`python/ray/autoscaler/_private/` (the KubeRay provider,
`kuberay/node_provider.py`, is the closest shape: translate autoscaler
create/terminate calls into REST operations against a managed API and poll
the resource state). Here the managed API is the Cloud TPU v2 surface
(`projects.locations.nodes` create / get / list / delete): one autoscaler
node == one TPU SLICE (`acceleratorType` like "v5litepod-16"), because TPU
capacity arrives in slices, not single hosts.

Transport is injectable: production uses HTTPS against
tpu.googleapis.com; tests inject `InMemoryTPUAPI`, an in-memory
implementation of the same REST verbs, so slice-granular scale-up is
exercised hermetically (this environment has zero egress).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from .node_provider import (
    NodeProvider,
    STATUS_TERMINATED,
    TAG_NODE_STATUS,
)

_API_ROOT = "https://tpu.googleapis.com/v2"


def _https_transport(method: str, url: str, body: Optional[dict]) -> dict:
    """Default transport (production): REST over urllib with an access token
    from the metadata server / env. Untestable here (zero egress) — tests
    inject InMemoryTPUAPI.transport instead."""
    import os
    import urllib.request

    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={
            "Content-Type": "application/json",
            "Authorization": f"Bearer {os.environ.get('GCP_ACCESS_TOKEN', '')}",
        },
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


class TPUVMProvider(NodeProvider):
    """provider_config keys:
        project, zone            — GCE location
        accelerator_type         — e.g. "v5litepod-16" (the SLICE unit)
        runtime_version          — e.g. "v2-alpha-tpuv5-lite"
        transport                — optional callable(method, url, body)->dict
    """

    def __init__(self, provider_config: dict, cluster_name: str = "ray-tpu"):
        super().__init__(provider_config, cluster_name)
        self.project = provider_config["project"]
        self.zone = provider_config["zone"]
        self.transport: Callable = provider_config.get(
            "transport", _https_transport
        )
        self._lock = threading.Lock()
        self._tag_cache: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------- helpers
    def _parent(self) -> str:
        return f"{_API_ROOT}/projects/{self.project}/locations/{self.zone}"

    def _node_url(self, node_id: str) -> str:
        return f"{self._parent()}/nodes/{node_id}"

    def _list(self) -> List[dict]:
        out = self.transport("GET", f"{self._parent()}/nodes", None)
        return out.get("nodes", [])

    # ------------------------------------------------------- NodeProvider
    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        nodes = []
        for n in self._list():
            if n.get("state") in ("DELETING", "TERMINATED"):
                continue
            labels = n.get("labels", {})
            if all(labels.get(k) == v for k, v in tag_filters.items()):
                node_id = n["name"].rsplit("/", 1)[-1]
                with self._lock:
                    self._tag_cache[node_id] = dict(labels)
                nodes.append(node_id)
        return nodes

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            cached = self._tag_cache.get(node_id)
        if cached is not None:
            return cached
        n = self.transport("GET", self._node_url(node_id), None)
        return n.get("labels", {})

    def is_running(self, node_id: str) -> bool:
        try:
            n = self.transport("GET", self._node_url(node_id), None)
        except Exception:  # noqa: BLE001
            return False
        return n.get("state") == "READY"

    def create_node(
        self, node_config: dict, tags: Dict[str, str], count: int
    ) -> List[str]:
        """One CREATE per slice — `count` slices, never partial hosts."""
        created = []
        accel = node_config.get(
            "accelerator_type", self.provider_config.get("accelerator_type")
        )
        runtime = node_config.get(
            "runtime_version",
            self.provider_config.get("runtime_version", "v2-alpha-tpuv5-lite"),
        )
        for _ in range(count):
            node_id = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            body = {
                "acceleratorType": accel,
                "runtimeVersion": runtime,
                "labels": {**tags, "ray-cluster": self.cluster_name},
                "metadata": {
                    "startup-script": node_config.get("startup_script", ""),
                },
            }
            self.transport(
                "POST", f"{self._parent()}/nodes?nodeId={node_id}", body
            )
            with self._lock:
                self._tag_cache[node_id] = dict(body["labels"])
            created.append(node_id)
        return created

    def terminate_node(self, node_id: str) -> None:
        self.transport("DELETE", self._node_url(node_id), None)
        with self._lock:
            tags = self._tag_cache.get(node_id)
            if tags is not None:
                tags[TAG_NODE_STATUS] = STATUS_TERMINATED


class InMemoryTPUAPI:
    """Hermetic double of the Cloud TPU REST surface (create/get/list/
    delete on `projects.locations.nodes`) — nodes move CREATING → READY
    after `provision_delay_s`, mirroring real slice provisioning."""

    def __init__(self, provision_delay_s: float = 0.0):
        self.nodes: Dict[str, dict] = {}
        self.provision_delay_s = provision_delay_s
        self.calls: List[tuple] = []
        self._lock = threading.Lock()

    def transport(self, method: str, url: str, body: Optional[dict]) -> dict:
        with self._lock:
            self.calls.append((method, url))
            if method == "POST":
                node_id = url.rsplit("nodeId=", 1)[-1]
                self.nodes[node_id] = {
                    "name": f"nodes/{node_id}",
                    "state": "CREATING",
                    "created_at": time.monotonic(),
                    **(body or {}),
                }
                return {"name": f"operations/{uuid.uuid4().hex}"}
            if method == "DELETE":
                node_id = url.rsplit("/", 1)[-1]
                node = self.nodes.get(node_id)
                if node is not None:
                    node["state"] = "TERMINATED"
                return {}
            # GET
            self._advance()
            if url.endswith("/nodes"):
                return {"nodes": [dict(n) for n in self.nodes.values()]}
            node_id = url.rsplit("/", 1)[-1]
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(node_id)
            return dict(node)

    def _advance(self):
        now = time.monotonic()
        for n in self.nodes.values():
            if (
                n["state"] == "CREATING"
                and now - n["created_at"] >= self.provision_delay_s
            ):
                n["state"] = "READY"
