"""LoadMetrics — the autoscaler's view of demand and utilization.

Reference analog: `python/ray/autoscaler/_private/load_metrics.py:63` —
aggregated from GCS resource batches there; here one `load_metrics` RPC to
the controller returns the whole picture (single control process).
"""

from __future__ import annotations

from typing import Dict, List


class LoadMetrics:
    def __init__(self):
        self.pending_demands: List[Dict[str, float]] = []
        self.pending_pg_bundles: List[Dict[str, float]] = []
        self.strict_spread_groups: List[dict] = []  # {"bundles": [...], "occupied": [...]}
        self.explicit_demands: List[Dict[str, float]] = []
        self.nodes: List[dict] = []  # controller node reports

    def update(self, raw: dict):
        self.pending_demands = raw.get("pending_demands", [])
        self.explicit_demands = raw.get("explicit_demands", [])
        # STRICT_SPREAD groups keep their identity — each bundle needs a
        # DISTINCT node, which plain bin-packing would violate (co-packing
        # two bundles onto one planned node would under-launch and deadlock
        # the PG). Other strategies flatten into ordinary demands.
        self.pending_pg_bundles = [
            dict(b)
            for pg in raw.get("pending_pgs", [])
            if pg.get("strategy") != "STRICT_SPREAD"
            for b in pg.get("bundles", [])
        ]
        self.strict_spread_groups = [
            {
                "bundles": [dict(b) for b in pg.get("bundles", [])],
                "occupied": list(pg.get("occupied", [])),
            }
            for pg in raw.get("pending_pgs", [])
            if pg.get("strategy") == "STRICT_SPREAD"
        ]
        self.nodes = raw.get("nodes", [])

    # ------------------------------------------------------------- derived
    def unmet_demands(self) -> List[Dict[str, float]]:
        """Every bundle the cluster has queued but cannot run right now,
        plus pending PG bundles and the explicit `request_resources` floor
        (the latter is a floor on *capacity*, so it is matched against node
        totals by the demand scheduler, not queued tasks)."""
        return [d for d in self.pending_demands if d] + self.pending_pg_bundles

    def idle_nodes(self, idle_timeout_s: float) -> List[str]:
        return [
            n["node_id"]
            for n in self.nodes
            if n["alive"] and not n["is_head"] and n["idle_s"] >= idle_timeout_s
        ]

    def alive_node_avail(self) -> Dict[str, Dict[str, float]]:
        return {
            n["node_id"]: dict(n["available"]) for n in self.nodes if n["alive"]
        }

    def alive_node_total(self) -> Dict[str, Dict[str, float]]:
        return {n["node_id"]: dict(n["total"]) for n in self.nodes if n["alive"]}
