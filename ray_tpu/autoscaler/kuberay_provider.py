"""KubeRay-style node provider — scale by patching a RayCluster custom
resource; an operator reconciles pods.

Reference analog: `python/ray/autoscaler/_private/kuberay/node_provider.py`
— the autoscaler never creates machines itself on Kubernetes: it PATCHes
the RayCluster CR's `workerGroupSpecs[].replicas` (and names doomed pods in
`scaleStrategy.workersToDelete`), and the KubeRay operator converges pods
to the spec. "Nodes" are the pods carrying the cluster label.

TPU redesign: worker groups are SLICE-granular. A group with
`numOfHosts: k` (the KubeRay TPU convention — one multi-host slice is k
pods that must exist together) scales in whole replicas; terminating any
pod of a replica removes the whole replica, because a partial TPU slice
can do no useful SPMD work.

Transport is injectable: production speaks to the in-cluster apiserver
(service-account token); tests inject `InMemoryK8sAPI`, which doubles BOTH
the apiserver verbs and the operator's reconcile loop, so scale-up/down is
exercised hermetically (zero egress here).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from .node_provider import (
    NodeProvider,
    TAG_NODE_KIND,
    TAG_NODE_TYPE,
)


def _in_cluster_transport(method: str, path: str, body: Optional[dict]) -> dict:
    """Default transport (production): apiserver REST with the pod's
    service-account token. Untestable here — tests inject InMemoryK8sAPI."""
    import json
    import os
    import urllib.request

    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    token = ""
    if os.path.exists(token_path):
        with open(token_path) as f:
            token = f.read().strip()
    req = urllib.request.Request(
        f"https://{host}:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": (
                "application/merge-patch+json" if method == "PATCH"
                else "application/json"
            ),
        },
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


class KubeRayProvider(NodeProvider):
    """provider_config keys:
        namespace          — k8s namespace of the RayCluster
        raycluster_name    — CR name (defaults to cluster_name)
        transport          — optional callable(method, path, body) -> dict
    """

    GROUP_KEY = "ray_tpu-group"  # pod label: which workerGroupSpec

    def __init__(self, provider_config: dict, cluster_name: str = "ray-tpu"):
        super().__init__(provider_config, cluster_name)
        self.namespace = provider_config.get("namespace", "default")
        self.cr_name = provider_config.get("raycluster_name", cluster_name)
        self.transport: Callable = provider_config.get(
            "transport", _in_cluster_transport
        )
        self._lock = threading.Lock()
        self._tag_cache: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------- helpers
    def _cr_path(self) -> str:
        return (
            f"/apis/ray.io/v1/namespaces/{self.namespace}"
            f"/rayclusters/{self.cr_name}"
        )

    def _pods_path(self) -> str:
        return (
            f"/api/v1/namespaces/{self.namespace}/pods"
            f"?labelSelector=ray.io/cluster={self.cr_name}"
        )

    def _get_cr(self) -> dict:
        return self.transport("GET", self._cr_path(), None)

    def _patch_cr(self, patch: dict) -> dict:
        return self.transport("PATCH", self._cr_path(), patch)

    def _pods(self) -> List[dict]:
        return self.transport("GET", self._pods_path(), None).get("items", [])

    def _group_spec(self, cr: dict, group: str) -> Optional[dict]:
        for g in cr["spec"].get("workerGroupSpecs", []):
            if g["groupName"] == group:
                return g
        return None

    def _groups_with(self, cr: dict, group: str, **changes) -> List[dict]:
        """The COMPLETE workerGroupSpecs array with one group modified.
        RFC 7386 merge-patch replaces arrays wholesale — patching a
        one-element list would delete every other worker group and strip
        the patched group's template, so every patch ships the full
        read-modify-write array (the reference provider does the same:
        `kuberay/node_provider.py` patches the whole workerGroupSpecs)."""
        import copy

        groups = copy.deepcopy(cr["spec"].get("workerGroupSpecs", []))
        for g in groups:
            if g["groupName"] == group:
                g.update(copy.deepcopy(changes))
        return groups

    # ------------------------------------------------------- NodeProvider
    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        out = []
        for pod in self._pods():
            if pod["status"].get("phase") in ("Succeeded", "Failed"):
                continue
            if pod["metadata"].get("deletionTimestamp"):
                continue
            labels = pod["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in tag_filters.items()):
                name = pod["metadata"]["name"]
                with self._lock:
                    self._tag_cache[name] = dict(labels)
                out.append(name)
        return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            cached = self._tag_cache.get(node_id)
        if cached is not None:
            return cached
        for pod in self._pods():
            if pod["metadata"]["name"] == node_id:
                return pod["metadata"].get("labels", {})
        return {}

    def is_running(self, node_id: str) -> bool:
        for pod in self._pods():
            if pod["metadata"]["name"] == node_id:
                return pod["status"].get("phase") == "Running"
        return False

    def create_node(
        self, node_config: dict, tags: Dict[str, str], count: int
    ) -> List[str]:
        """Scale-up = bump the group's replica count; the operator makes
        pods. Returns [] — pods surface through non_terminated_nodes once
        reconciled (the reference provider is likewise asynchronous)."""
        group = node_config.get("group", tags.get(TAG_NODE_TYPE, "workers"))
        cr = self._get_cr()
        spec = self._group_spec(cr, group)
        if spec is None:
            raise ValueError(
                f"RayCluster {self.cr_name} has no worker group {group!r}"
            )
        self._patch_cr({
            "spec": {
                "workerGroupSpecs": self._groups_with(
                    cr, group, replicas=int(spec.get("replicas", 0)) + count
                )
            }
        })
        return []

    def terminate_node(self, node_id: str) -> None:
        """Scale-down is REPLICA-granular: name the pod in workersToDelete
        and drop the replica count; for a multi-host (TPU slice) group the
        operator removes every pod of that replica — a partial slice cannot
        run SPMD work."""
        tags = self.node_tags(node_id)
        group = tags.get(self.GROUP_KEY) or tags.get(TAG_NODE_TYPE, "workers")
        cr = self._get_cr()
        spec = self._group_spec(cr, group)
        if spec is None:
            return
        self._patch_cr({
            "spec": {
                "workerGroupSpecs": self._groups_with(
                    cr, group,
                    replicas=max(0, int(spec.get("replicas", 0)) - 1),
                    scaleStrategy={
                        "workersToDelete":
                            spec.get("scaleStrategy", {}).get(
                                "workersToDelete", []
                            ) + [node_id],
                    },
                )
            }
        })

    def shutdown(self):
        pass


# ---------------------------------------------------------------- test double
class InMemoryK8sAPI:
    """Hermetic double of the apiserver + KubeRay operator: PATCHed replica
    counts reconcile into pods (Pending → Running after
    `provision_delay_s`); workersToDelete removes the named pod's whole
    replica (numOfHosts pods for multi-host TPU groups)."""

    def __init__(self, raycluster: dict, provision_delay_s: float = 0.0):
        self.cr = raycluster
        self.provision_delay_s = provision_delay_s
        self.pods: Dict[str, dict] = {}
        self.calls: List[tuple] = []
        self._replica_seq: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._reconcile()

    # -------------------------------------------------------- REST double
    def transport(self, method: str, path: str, body: Optional[dict]) -> dict:
        with self._lock:
            self.calls.append((method, path))
            if "/rayclusters/" in path:
                if method == "GET":
                    return self._copy_cr()
                if method == "PATCH":
                    self._merge_patch(body or {})
                    self._reconcile()
                    return self._copy_cr()
            if method == "GET" and "/pods" in path:
                self._advance()
                return {"items": [dict(p) for p in self.pods.values()]}
            raise ValueError(f"unhandled {method} {path}")

    def _copy_cr(self) -> dict:
        import copy

        return copy.deepcopy(self.cr)

    def _merge_patch(self, patch: dict):
        """RFC 7386 semantics — dicts merge recursively, arrays and scalars
        REPLACE wholesale, null deletes. Faithful to a real apiserver so the
        provider can't pass tests with patches that would destroy sibling
        worker groups in production."""
        import copy

        def merge(target: dict, p: dict):
            for k, v in p.items():
                if v is None:
                    target.pop(k, None)
                elif isinstance(v, dict) and isinstance(target.get(k), dict):
                    merge(target[k], v)
                else:
                    target[k] = copy.deepcopy(v)

        merge(self.cr, patch)

    # ---------------------------------------------------- operator double
    def _reconcile(self):
        cluster = self.cr["metadata"]["name"]
        for spec in self.cr["spec"]["workerGroupSpecs"]:
            group = spec["groupName"]
            hosts = int(spec.get("numOfHosts", 1))
            # Deletion first (mirrors the operator: doomed workers go away
            # before replica arithmetic is reconciled).
            doomed = set(
                spec.get("scaleStrategy", {}).get("workersToDelete", [])
            )
            doomed_replicas = {
                p["metadata"]["labels"]["replica-index"]
                for name, p in self.pods.items()
                if name in doomed
            }
            for name, p in list(self.pods.items()):
                if (
                    p["metadata"]["labels"][KubeRayProvider.GROUP_KEY] == group
                    and p["metadata"]["labels"]["replica-index"]
                    in doomed_replicas
                ):
                    del self.pods[name]
            if doomed:
                spec.setdefault("scaleStrategy", {})["workersToDelete"] = []
            live_replicas = {
                p["metadata"]["labels"]["replica-index"]
                for p in self.pods.values()
                if p["metadata"]["labels"][KubeRayProvider.GROUP_KEY] == group
            }
            want = int(spec.get("replicas", 0))
            while len(live_replicas) < want:
                seq = self._replica_seq.get(group, 0)
                self._replica_seq[group] = seq + 1
                ridx = f"{group}-{seq}"
                for h in range(hosts):
                    name = f"{cluster}-{ridx}-{h}"
                    self.pods[name] = {
                        "metadata": {
                            "name": name,
                            "labels": {
                                "ray.io/cluster": cluster,
                                KubeRayProvider.GROUP_KEY: group,
                                "replica-index": ridx,
                                **spec.get("labels", {}),
                            },
                        },
                        "status": {"phase": "Pending"},
                        "_created": time.monotonic(),
                    }
                live_replicas.add(ridx)

    def _advance(self):
        now = time.monotonic()
        for p in self.pods.values():
            if (
                p["status"]["phase"] == "Pending"
                and now - p["_created"] >= self.provision_delay_s
            ):
                p["status"]["phase"] = "Running"
