"""Autoscaler — demand-driven cluster scaling.

Reference analog: `python/ray/autoscaler/_private/autoscaler.py`
(`StandardAutoscaler.update` :171,373) driven by `LoadMetrics`
(`load_metrics.py:63`) and `resource_demand_scheduler.py` bin-packing, with
pluggable `NodeProvider`s (fake multinode provider for hermetic tests:
`autoscaler/_private/fake_multi_node/node_provider.py`).

Redesign (TPU-first): the controller already holds the whole demand picture
(ready queue, pending placement groups, explicit requests) in one process, so
`LoadMetrics` is a single `load_metrics` RPC instead of a GCS-batched
resource stream. Node types describe whole TPU hosts (a v5e host = one node
with `{"CPU": N, "TPU": 4}`), so scaling a slice gang = bin-packing its
STRICT_SPREAD placement-group bundles onto `tpu_node` types.
"""

from .autoscaler import Monitor, StandardAutoscaler
from .load_metrics import LoadMetrics
from .node_provider import FakeMultiNodeProvider, NodeProvider
from .tpu_vm_provider import InMemoryTPUAPI, TPUVMProvider
from .resource_demand_scheduler import get_nodes_to_launch
from . import sdk

__all__ = [
    "StandardAutoscaler",
    "Monitor",
    "LoadMetrics",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "TPUVMProvider",
    "InMemoryTPUAPI",
    "get_nodes_to_launch",
    "sdk",
]
