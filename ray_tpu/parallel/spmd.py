"""SPMD execution helpers: jit-with-shardings and shard_map wrappers.

Reference analog: none — this replaces the entire NCCL worker-group data
plane (`ray.util.collective`, torch DDP in `train/torch/train_loop_utils.py`)
with compiled XLA programs over a Mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax

from .mesh import ShardingRules


def parallelize(
    fn: Callable,
    mesh,
    in_shardings=None,
    out_shardings=None,
    static_argnums=(),
    donate_argnums=(),
) -> Callable:
    """jit `fn` over `mesh` with explicit shardings (pjit idiom).

    Shardings may be NamedSharding, PartitionSpec (resolved against `mesh`),
    or None (let XLA propagate).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    def resolve(s):
        if s is None or isinstance(s, NamedSharding):
            return s
        if isinstance(s, PartitionSpec):
            return NamedSharding(mesh, s)
        if isinstance(s, (tuple, list)):
            return type(s)(resolve(x) for x in s)
        if isinstance(s, dict):
            return {k: resolve(v) for k, v in s.items()}
        return s

    jitted = jax.jit(
        fn,
        in_shardings=resolve(in_shardings) if in_shardings is not None else None,
        out_shardings=resolve(out_shardings) if out_shardings is not None else None,
        static_argnums=static_argnums,
        donate_argnums=donate_argnums,
    )

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else _nullcontext():
            return jitted(*args, **kwargs)

    wrapper.jitted = jitted
    wrapper.lower = jitted.lower
    return wrapper


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


def shard_fn(
    fn: Callable,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = False,
    manual_axes: Optional[frozenset] = None,
) -> Callable:
    """`shard_map` wrapper: per-device function with explicit collectives.

    This is where ring attention, Ulysses all-to-all, and hand-written
    pipeline schedules live — code inside `fn` sees its local shard and the
    mesh axis names are bound for `jax.lax.p*`.

    `manual_axes` restricts manual collectives to a subset of mesh axes; the
    rest stay AUTO — the compiler keeps partitioning the body over them
    (e.g. a pipeline manual over `pp` whose stages still auto-shard over
    dp/fsdp/tp).
    """
    if hasattr(jax, "shard_map"):
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if manual_axes is not None:
            kwargs["axis_names"] = frozenset(manual_axes)
        return jax.shard_map(fn, **kwargs)
    from jax.experimental.shard_map import shard_map  # older jax fallback

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
    if manual_axes is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(manual_axes)
    return shard_map(fn, **kwargs)
