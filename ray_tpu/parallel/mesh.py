"""Device mesh construction — the substrate every parallelism strategy rides.

Reference analog: none (Ray delegates in-node parallelism to NCCL process
groups — `python/ray/util/collective/collective_group/nccl_collective_group.py`).
TPU-first redesign: parallelism is expressed as a `jax.sharding.Mesh` with
named axes; XLA compiles collectives onto ICI. The canonical axes:

    dp    — data parallel (pure replica)
    fsdp  — fully-sharded data parallel (ZeRO-style weight sharding)
    tp    — tensor (model) parallel
    sp    — sequence/context parallel (ring attention rides this axis)
    ep    — expert parallel (MoE)
    pp    — pipeline stage (usually across DCN, not ICI)

`MeshSpec` resolves partially-specified axis sizes against the actual device
count (one `-1` axis absorbs the remainder, like a reshape).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Named-axis mesh specification.

    >>> MeshSpec(dp=-1, tp=4).build()   # tp innermost → rides fastest ICI links
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, num_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"At most one axis may be -1, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = num_devices // known
        elif known != num_devices:
            raise ValueError(
                f"Mesh {sizes} wants {known} devices but {num_devices} are available"
            )
        return MeshSpec(**sizes)

    def build(self, devices: Optional[Sequence] = None):
        """Create the `jax.sharding.Mesh`.

        Axis order puts `tp` (then `ep`, `sp`) innermost so the heaviest
        collectives map onto nearest-neighbor ICI links; `pp`/`dp` outermost
        (cheapest traffic, tolerates DCN hops on multi-slice).
        """
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        spec = self.resolve(len(devices))
        shape = tuple(spec.sizes()[a] for a in AXIS_ORDER)
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, AXIS_ORDER)

    @property
    def num_devices(self) -> int:
        return math.prod(s for s in self.sizes().values() if s != -1)


def make_mesh(devices=None, **axis_sizes) -> "jax.sharding.Mesh":  # noqa: F821
    """`make_mesh(dp=-1, tp=4)` → Mesh. Unmentioned axes are size 1."""
    return MeshSpec(**axis_sizes).build(devices)


# --------------------------------------------------------------- logical axes
@dataclass
class ShardingRules:
    """Logical-axis → mesh-axis rules (the t5x/maxtext idiom, re-derived).

    Model code annotates arrays with *logical* dim names; the rules decide
    which mesh axes they shard over. One place to retarget a model from pure
    DP to 3D DP×FSDP×TP without touching model code.
    """

    rules: Dict[str, Optional[Tuple[str, ...]]] = field(default_factory=dict)

    @classmethod
    def default(cls) -> "ShardingRules":
        return cls(
            rules={
                # Activations.
                "batch": ("dp", "fsdp"),
                "seq": ("sp",),
                "embed_act": None,           # activations replicated over tp...
                "heads_act": ("tp",),        # ...but heads split over tp
                "mlp_act": ("tp",),
                # Weights.
                "embed": ("fsdp",),          # ZeRO-shard the embed dim
                "heads": ("tp",),
                "kv_heads": ("tp",),
                "head_dim": None,
                "mlp": ("tp",),
                "vocab": ("tp",),
                "experts": ("ep",),
                "layers": None,              # scanned layer axis stays unsharded
                "stage": ("pp",),
            }
        )

    def spec(self, *logical_dims: Optional[str]):
        """Logical dims → `PartitionSpec`."""
        from jax.sharding import PartitionSpec

        out = []
        for dim in logical_dims:
            if dim is None:
                out.append(None)
            else:
                if dim not in self.rules:
                    # A typo'd dim silently replicating would surface only as
                    # an OOM/perf mystery at scale — fail loudly at trace time.
                    raise KeyError(
                        f"Unknown logical dim {dim!r}; known: {sorted(self.rules)}. "
                        "Map it explicitly (None = replicated) via with_rules()."
                    )
                axes = self.rules[dim]
                if axes is None:
                    out.append(None)
                elif len(axes) == 1:
                    out.append(axes[0])
                else:
                    out.append(tuple(axes))
        return PartitionSpec(*out)

    def sharding(self, mesh, *logical_dims):
        from jax.sharding import NamedSharding

        # Drop mesh axes of size 1 so specs stay valid on degenerate meshes.
        spec = self.spec(*logical_dims)
        cleaned = []
        for entry in spec:
            if entry is None:
                cleaned.append(None)
            elif isinstance(entry, tuple):
                kept = tuple(a for a in entry if mesh.shape.get(a, 1) > 1)
                # Unwrap singletons like spec() does — this jax's
                # PartitionSpec treats ('dp',) and 'dp' as distinct.
                cleaned.append(kept[0] if len(kept) == 1 else (kept or None))
            else:
                cleaned.append(entry if mesh.shape.get(entry, 1) > 1 else None)
        from jax.sharding import PartitionSpec

        return NamedSharding(mesh, PartitionSpec(*cleaned))

    def with_rules(self, **overrides) -> "ShardingRules":
        new = dict(self.rules)
        for k, v in overrides.items():
            new[k] = tuple(v) if isinstance(v, (list, tuple)) else ((v,) if v else None)
        return ShardingRules(rules=new)


def constrain(x, mesh, rules: ShardingRules, *logical_dims):
    """`lax.with_sharding_constraint` via logical dims.

    Errors (rank mismatch, unknown dims) propagate — silent fallback would
    hide missing shardings until they show up as OOMs at scale.
    """
    import jax

    return jax.lax.with_sharding_constraint(x, rules.sharding(mesh, *logical_dims))
