from .mesh import AXIS_ORDER, MeshSpec, ShardingRules, constrain, make_mesh
from .pipeline import (
    make_gpipe_fn,
    make_pipelined_loss_fn,
    merge_microbatches,
    split_microbatches,
    stack_stage_params,
)
from .spmd import parallelize, shard_fn

__all__ = [
    "AXIS_ORDER",
    "MeshSpec",
    "ShardingRules",
    "make_mesh",
    "constrain",
    "parallelize",
    "shard_fn",
    "make_gpipe_fn",
    "make_pipelined_loss_fn",
    "split_microbatches",
    "merge_microbatches",
    "stack_stage_params",
]
