from .mesh import AXIS_ORDER, MeshSpec, ShardingRules, constrain, make_mesh
from .spmd import parallelize, shard_fn

__all__ = [
    "AXIS_ORDER",
    "MeshSpec",
    "ShardingRules",
    "make_mesh",
    "constrain",
    "parallelize",
    "shard_fn",
]
