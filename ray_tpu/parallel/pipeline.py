"""Pipeline parallelism as a compiled XLA program (GPipe schedule).

Reference analog: `python/ray/dag/compiled_dag_node.py` + channels is Ray's
*substrate* for pipelines (SURVEY.md §2.6 — no actual schedule exists there).
TPU-native design: the whole pipeline lives INSIDE one jit program over the
`pp` mesh axis — each device holds one stage's weights, microbatches flow
stage-to-stage via `ppermute` over ICI, and the 1F1B/GPipe *backward*
schedule emerges automatically from jax AD transposing the forward scan
(ppermute's transpose is the reverse ppermute). No host-side scheduling, no
channel round-trips, no NCCL.

Cross-host pipelines over DCN use the compiled-DAG channel planes instead:
`ray_tpu.train.mpmd` runs each stage as a SEPARATE jit program on its own
gang actor with a host-side 1F1B schedule (same `stage_fn(params, act)`
shape as `make_gpipe_fn` takes here), which is the path that composes with
per-stage data parallelism + ZeRO sharded updates and elastic reshapes.
This in-jit GPipe remains the single-program baseline the MPMD parity gate
measures against (tests/test_train_mpmd.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .spmd import shard_fn


def split_microbatches(x, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...]."""
    B = x.shape[0]
    if B % num_microbatches != 0:
        raise ValueError(f"batch {B} not divisible by num_microbatches {num_microbatches}")
    return x.reshape(num_microbatches, B // num_microbatches, *x.shape[1:])


def merge_microbatches(x):
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def stack_stage_params(per_stage_params: list):
    """List of per-stage pytrees -> one pytree with leading stage axis
    (shard it P('pp') so each device holds exactly its stage)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def make_gpipe_fn(
    stage_fn: Callable,
    mesh,
    *,
    num_microbatches: int,
    axis: str = "pp",
    params_spec=None,
    x_spec=P(),
):
    """Build `f(stacked_params, x_microbatched) -> y_microbatched`.

    stage_fn(stage_params, activation) -> activation, applied S times (S =
    mesh.shape[axis]); stacked_params has a leading [S] stage axis; x is
    [M, mb, ...] microbatched input. The returned function is shard_map'ed
    over `axis` and differentiable end-to-end.
    """
    S = mesh.shape[axis]
    M = num_microbatches

    def per_device(stacked_params, x):
        params = jax.tree.map(lambda p: p[0], stacked_params)  # local stage
        s = lax.axis_index(axis)
        is_first = s == 0
        is_last = s == S - 1
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        mb_shape = x.shape[1:]
        outs0 = jnp.zeros((M,) + mb_shape, x.dtype)
        act0 = jnp.zeros(mb_shape, x.dtype)

        def tick(carry, t):
            act_in, outs = carry
            # Stage 0 injects microbatch t (clamped once the tail drains).
            x_t = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(is_first, x_t, act_in)
            y = stage_fn(params, inp)
            # Microbatch t leaves stage S-1 at tick t + S - 1.
            write_idx = jnp.clip(t - (S - 1), 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(outs, y, write_idx, 0)
            outs = jnp.where(jnp.logical_and(is_last, t >= S - 1), updated, outs)
            act_next = lax.ppermute(y, axis, fwd_perm)
            return (act_next, outs), None

        (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(M + S - 1))
        # Only stage S-1 holds real outputs (others kept zeros) — psum
        # replicates the result to every stage.
        return lax.psum(jnp.where(is_last, outs, jnp.zeros_like(outs)), axis)

    if params_spec is None:
        params_spec = P(axis)
    return shard_fn(
        per_device, mesh, in_specs=(params_spec, x_spec), out_specs=x_spec
    )


def make_pipelined_loss_fn(
    stage_fn: Callable,
    loss_fn: Callable,
    mesh,
    *,
    num_microbatches: int,
    axis: str = "pp",
):
    """`f(stacked_params, batch_x, batch_target) -> scalar loss` with the
    pipeline inside; differentiable (GPipe backward via AD)."""
    gpipe = make_gpipe_fn(stage_fn, mesh, num_microbatches=num_microbatches, axis=axis)

    def fn(stacked_params, x, target):
        y = merge_microbatches(gpipe(stacked_params, split_microbatches(x, num_microbatches)))
        return loss_fn(y, target)

    return fn
