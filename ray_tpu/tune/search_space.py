"""Search-space primitives (reference: `python/ray/tune/search/sample.py` +
`grid_search`). Samplers are plain objects resolved by the variant generator."""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class QUniform(Domain):
    def __init__(self, low: float, high: float, q: float):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        value = rng.uniform(self.low, self.high)
        return round(value / self.q) * self.q


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class LogRandInt(Domain):
    def __init__(self, low: int, high: int):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return int(round(math.exp(rng.uniform(self.log_low, self.log_high))))


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class RandN(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


# Public constructors (reference API names).
def uniform(low, high):
    return Uniform(low, high)


def loguniform(low, high):
    return LogUniform(low, high)


def quniform(low, high, q):
    return QUniform(low, high, q)


def randint(low, high):
    return RandInt(low, high)


def lograndint(low, high):
    return LogRandInt(low, high)


def choice(categories):
    return Choice(categories)


def randn(mean=0.0, sd=1.0):
    return RandN(mean, sd)


def sample_from(fn):
    return Function(fn)


def grid_search(values):
    return GridSearch(values)


def resolve_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand grid_search entries into the cartesian product of variants."""
    import itertools

    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [dict(space)]
    grids = [space[k].values for k in grid_keys]
    variants = []
    for combo in itertools.product(*grids):
        v = dict(space)
        for k, val in zip(grid_keys, combo):
            v[k] = val
        variants.append(v)
    return variants


def sample_variant(variant: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    out = {}
    for k, v in variant.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = sample_variant(v, rng)
        else:
            out[k] = v
    return out
