"""Tuner + TuneController (reference: `python/ray/tune/tuner.py`,
`tune/execution/tune_controller.py:72` — the event loop at `step` `:709`).

Trials run as TrainWorker actors (shared mechanism with ray_tpu.train —
the reference likewise funnels Train through Tune trial actors,
`base_trainer.py:839`); the controller polls results, drives the scheduler
(ASHA/PBT/...), the searcher, and checkpoint bookkeeping.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..core import api
from ..train.checkpoint import Checkpoint
from ..train.config import RunConfig
from ..train.result import Result
from .schedulers import CONTINUE, STOP, FIFOScheduler, PopulationBasedTraining, TrialScheduler
from .search import BUSY, BasicVariantGenerator, Searcher


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    trial_resources: Optional[Dict[str, float]] = None


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.state = "PENDING"
        self.actor = None
        self.results: List[Dict[str, Any]] = []
        self.latest_checkpoint: Optional[Checkpoint] = None
        self.error: Optional[str] = None
        self.iteration = 0

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.results[-1] if self.results else {}


class TuneController:
    def __init__(
        self,
        trainable: Callable,
        tune_config: TuneConfig,
        run_config: RunConfig,
        param_space: Dict[str, Any],
        restore_state: Optional[dict] = None,
    ):
        self.trainable = trainable
        self.tune_config = tune_config
        self.run_config = run_config
        self.metric = tune_config.metric
        self.mode = tune_config.mode
        self.searcher = tune_config.search_alg or BasicVariantGenerator(
            param_space, num_samples=tune_config.num_samples
        )
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        if self.metric:
            self.searcher.set_objective(self.metric, self.mode)
            self.scheduler.set_objective(self.metric, self.mode)
        self.trials: List[Trial] = []
        self._trial_counter = itertools.count()
        self._exhausted = False
        if restore_state is not None:
            # Experiment-level resume (reference:
            # `tune/execution/experiment_state.py` + `Tuner.restore`):
            # terminal trials keep their results; interrupted ones re-run
            # from their latest checkpoint with their original config.
            self.searcher = restore_state["searcher"]
            self._exhausted = restore_state["exhausted"]
            for td in restore_state["trials"]:
                trial = Trial(td["trial_id"], td["config"])
                trial.results = td["results"]
                trial.latest_checkpoint = td["latest_checkpoint"]
                trial.error = td["error"]
                trial.iteration = td["iteration"]
                trial.state = (
                    td["state"] if td["state"] in ("TERMINATED", "ERROR")
                    else "RESTORE_PENDING"
                )
                self.trials.append(trial)
                if trial.state == "RESTORE_PENDING":
                    self.scheduler.on_trial_add(trial)

    # ---------------------------------------------------- experiment state
    def _state_path(self) -> str:
        import os

        exp_dir = self.run_config.resolve_storage()  # already .../<name>
        os.makedirs(exp_dir, exist_ok=True)
        return os.path.join(exp_dir, "experiment_state.pkl")

    def _save_experiment_state(self):
        import os

        import cloudpickle

        state = {
            "searcher": self.searcher,
            "exhausted": self._exhausted,
            "metric": self.metric,
            "mode": self.mode,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "state": t.state,
                    "results": t.results,
                    "latest_checkpoint": t.latest_checkpoint,
                    "error": t.error,
                    "iteration": t.iteration,
                }
                for t in self.trials
            ],
        }
        path = self._state_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, path)

    # ------------------------------------------------------------- lifecycle
    def _next_trial(self):
        # Interrupted-then-restored trials launch before new suggestions.
        for t in self.trials:
            if t.state == "RESTORE_PENDING":
                t.state = "PENDING"
                return t
        if self._exhausted:
            return None
        trial_id = f"trial_{next(self._trial_counter):05d}_{uuid.uuid4().hex[:6]}"
        config = self.searcher.suggest(trial_id)
        if config is BUSY:
            return BUSY  # throttled (ConcurrencyLimiter) — retry next tick
        if config is None:
            self._exhausted = True
            # Synchronous schedulers (HyperBand) resolve partially-filled
            # brackets once they know no more trials are coming.
            if hasattr(self.scheduler, "on_no_more_trials"):
                self.scheduler.on_no_more_trials()
            return None
        trial = Trial(trial_id, config)
        self.trials.append(trial)
        self.scheduler.on_trial_add(trial)
        return trial

    def _start_trial(self, trial: Trial, checkpoint: Optional[Checkpoint] = None):
        import cloudpickle

        from ..train.worker_group import TrainWorker

        resources = self.tune_config.trial_resources or {"CPU": 1.0}
        remote_cls = api.remote(TrainWorker)
        trial.actor = remote_cls.options(
            num_cpus=resources.get("CPU", 1.0),
            num_tpus=resources.get("TPU") or None,
        ).remote(
            dict(
                world_rank=0,
                world_size=1,
                trial_id=trial.trial_id,
                trial_name=trial.trial_id,
                experiment_name=self.run_config.name or "tune",
                storage_path=self.run_config.resolve_storage(),
            )
        )
        if checkpoint is not None:
            api.get(trial.actor.set_checkpoint.remote(checkpoint))
            trial.latest_checkpoint = checkpoint
        api.get(
            trial.actor.run.remote(cloudpickle.dumps((self.trainable, trial.config)))
        )
        trial.state = "RUNNING"

    def _stop_trial(self, trial: Trial, state: str = "TERMINATED"):
        trial.state = state
        if trial.actor is not None:
            try:
                api.kill(trial.actor)
            except Exception:  # noqa: BLE001
                pass
            trial.actor = None

    # ------------------------------------------------------------------ loop
    def run(self) -> List[Trial]:
        max_conc = self.tune_config.max_concurrent_trials or 4
        stop_criteria = self.run_config.stop or {}

        while True:
            running = [t for t in self.trials if t.state == "RUNNING"]
            # Launch up to the concurrency cap.
            while len(running) < max_conc:
                trial = self._next_trial()
                if trial is None or trial is BUSY:
                    break
                self._start_trial(trial, checkpoint=trial.latest_checkpoint)
                running.append(trial)
            if not running:
                break  # launch loop above already probed _next_trial

            for trial in running:
                try:
                    results, finished, err = api.get(trial.actor.poll.remote(), timeout=60)
                except Exception as e:  # noqa: BLE001 — actor/worker died
                    trial.error = str(e)
                    self._stop_trial(trial, "ERROR")
                    self.searcher.on_trial_complete(trial.trial_id, None)
                    continue
                decision = CONTINUE
                restarted = False
                for entry in results:
                    metrics = entry["metrics"]
                    trial.iteration += 1
                    metrics.setdefault("training_iteration", trial.iteration)
                    metrics["trial_id"] = trial.trial_id
                    trial.results.append(metrics)
                    if entry.get("checkpoint") is not None:
                        trial.latest_checkpoint = entry["checkpoint"]
                    hook = getattr(self.searcher, "on_trial_result", None)
                    if hook is not None:  # BOHB: rung results feed the model
                        hook(trial.trial_id, metrics)
                    d = self.scheduler.on_trial_result(trial, metrics)
                    if d == STOP:
                        decision = STOP
                    if self._hit_stop_criteria(metrics, stop_criteria):
                        decision = STOP
                    if decision == STOP:
                        # Don't record results past the stopping point — a
                        # fast loop may have queued many more already.
                        break
                    if isinstance(self.scheduler, PopulationBasedTraining):
                        if self._maybe_pbt(trial, metrics):
                            # The old actor was replaced — results/finished
                            # flags from this poll belong to the dead actor.
                            restarted = True
                            break
                if restarted:
                    continue
                if err:
                    trial.error = err
                    self._stop_trial(trial, "ERROR")
                    self.searcher.on_trial_complete(trial.trial_id, None)
                    self._save_experiment_state()
                elif decision == STOP or finished:
                    self._stop_trial(trial)
                    self.scheduler.on_trial_complete(trial, trial.last_result)
                    self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
                    self._save_experiment_state()
            time.sleep(0.02)
        self._save_experiment_state()
        return self.trials

    def _hit_stop_criteria(self, metrics: Dict[str, Any], stop: Dict[str, Any]) -> bool:
        for key, bound in stop.items():
            v = metrics.get(key)
            if v is not None and v >= bound:
                return True
        return False

    def _maybe_pbt(self, trial: Trial, metrics: Dict[str, Any]) -> bool:
        """Returns True when the trial's actor was replaced."""
        pbt: PopulationBasedTraining = self.scheduler  # type: ignore[assignment]
        if not pbt.should_perturb(trial, metrics):
            return False
        target_id = pbt.exploit_target(trial)
        if target_id is None:
            return False
        target = next((t for t in self.trials if t.trial_id == target_id), None)
        if target is None or target.latest_checkpoint is None:
            return False
        # Exploit + explore: restart this trial from the target's checkpoint
        # with mutated hyperparams (reference: `pbt.py` _exploit).
        self._stop_trial(trial, "PAUSED")
        trial.config = pbt.explore(dict(target.config))
        self._start_trial(trial, checkpoint=target.latest_checkpoint)
        return True


# ------------------------------------------------------------------- public
class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __iter__(self):
        for t in self._trials:
            yield self._to_result(t)

    def _to_result(self, t: Trial) -> Result:
        return Result(
            metrics=t.last_result,
            checkpoint=t.latest_checkpoint,
            error=t.error,
            metrics_history=t.results,
        )

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("Specify `metric` (no default set in TuneConfig)")
        sign = 1.0 if mode == "max" else -1.0

        def best_score(t: Trial):
            scores = [sign * r[metric] for r in t.results if metric in r]
            return max(scores) if scores else float("-inf")

        candidates = [t for t in self._trials if t.results]
        if not candidates:
            raise RuntimeError("No trial reported any results")
        return self._to_result(max(candidates, key=best_score))

    @property
    def errors(self):
        return [t.error for t in self._trials if t.error]

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([t.last_result for t in self._trials])


class Tuner:
    """Reference: `ray.tune.Tuner` — Tuner(trainable, param_space=...,
    tune_config=TuneConfig(...), run_config=RunConfig(...)).fit()."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        from ..train.base_trainer import BaseTrainer

        if isinstance(trainable, BaseTrainer):
            trainer = trainable
            param_space = param_space or {}

            def trainable_fn(config):  # Trainer-as-trainable (reference
                # `base_trainer.py:839 as_trainable`).
                from ..train.session import report as session_report

                loop_cfg = dict(getattr(trainer, "train_loop_config", {}) or {})
                loop_cfg.update(config.get("train_loop_config", config))
                trainer.train_loop_config = loop_cfg
                result = trainer.fit()
                if result.error:
                    raise RuntimeError(result.error)
                # Surface the inner run's history to the tune session so the
                # controller/scheduler see this trial's metrics.
                for i, metrics in enumerate(result.metrics_history):
                    last = i == len(result.metrics_history) - 1
                    session_report(
                        metrics, checkpoint=result.checkpoint if last else None
                    )

            self.trainable = trainable_fn
        else:
            self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        controller = TuneController(
            self.trainable, self.tune_config, self.run_config,
            self.param_space, restore_state=getattr(self, "_restore_state", None),
        )
        trials = controller.run()
        return ResultGrid(trials, self.tune_config.metric, self.tune_config.mode)

    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Callable,
        *,
        run_config: Optional[RunConfig] = None,
    ) -> "Tuner":
        """Resume an interrupted experiment from its directory (reference:
        `Tuner.restore` + `tune/execution/experiment_state.py`). Terminal
        trials keep their results; interrupted trials re-run from their
        latest checkpoint."""
        import os

        import cloudpickle

        state_file = (
            path if path.endswith(".pkl")
            else os.path.join(path, "experiment_state.pkl")
        )
        with open(state_file, "rb") as f:
            state = cloudpickle.load(f)
        name = os.path.basename(os.path.dirname(os.path.abspath(state_file)))
        rc = run_config or RunConfig(name=name)
        rc.name = rc.name or name
        tuner = cls(
            trainable,
            tune_config=TuneConfig(metric=state["metric"], mode=state["mode"]),
            run_config=rc,
        )
        tuner._restore_state = state
        return tuner


def run(
    trainable: Callable,
    config: Optional[Dict[str, Any]] = None,
    *,
    metric: Optional[str] = None,
    mode: str = "max",
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    stop: Optional[dict] = None,
    max_concurrent_trials: Optional[int] = None,
    **_ignored,
) -> ResultGrid:
    """Functional API (reference: `tune.run`)."""
    tuner = Tuner(
        trainable,
        param_space=config or {},
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
        ),
        run_config=RunConfig(stop=stop),
    )
    return tuner.fit()
