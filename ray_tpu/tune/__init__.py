"""ray_tpu.tune — hyperparameter search (API parity: `ray.tune`, SURVEY.md
Appendix A: Tuner, TuneConfig, run, search-space ops, schedulers, searchers)."""

from ..train.checkpoint import Checkpoint
from ..train.session import get_checkpoint, get_context
from ..train.session import report as _session_report
from .schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (BOHBSearch, BasicVariantGenerator, ConcurrencyLimiter,
                     OptunaSearch, Searcher, TPESearch)
from .search_space import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from .tuner import ResultGrid, TuneConfig, TuneController, Tuner, run


def report(metrics, checkpoint=None, **kw):
    """Report metrics from a trial (reference: `ray.tune.report` /
    `session.report`). Extra kwargs are folded into the metrics dict."""
    merged = dict(metrics or {})
    merged.update(kw)
    _session_report(merged, checkpoint=checkpoint)


__all__ = [
    "Tuner",
    "TuneConfig",
    "run",
    "report",
    "get_context",
    "get_checkpoint",
    "Checkpoint",
    "ResultGrid",
    "TuneController",
    "uniform",
    "loguniform",
    "quniform",
    "randint",
    "lograndint",
    "choice",
    "randn",
    "sample_from",
    "grid_search",
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Searcher",
    "BasicVariantGenerator",
    "BOHBSearch",
    "ConcurrencyLimiter",
    "OptunaSearch",
    "TPESearch",
]
