"""Trial schedulers (reference: `python/ray/tune/schedulers/`): FIFO,
ASHA (async successive halving), HyperBand-lite, MedianStopping, PBT."""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_objective(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def on_trial_add(self, trial):
        """Called when a trial is created (before any result)."""

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]):
        pass

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: `schedulers/async_hyperband.py`): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped if
    it is below the top-1/reduction_factor quantile of scores recorded there."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: float = 3,
        max_t: int = 100,
        brackets: int = 1,
    ):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = defaultdict(list)

    def _milestones(self):
        out = []
        t = self.grace_period
        while t < self.max_t:
            out.append(int(t))
            t *= self.rf
        return out

    def on_trial_result(self, trial, result):
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for milestone in self._milestones():
            if t == milestone:
                rung = self._rungs[milestone]
                rung.append(score)
                k = max(1, int(len(rung) / self.rf))
                cutoff = sorted(rung, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: `schedulers/hyperband.py`).

    Brackets trade initial budget against halving count; bracket k starts at
    budget max_t * eta^-k with capacity n_k = ceil((s_max+1)/(k+1)) * eta^k.
    Trials fill the MOST aggressive bracket (largest k: cheapest budget,
    most halvings) first — canonical HyperBand order. A rung resolves when
    its full population reported it: the bracket's capacity once the bracket
    is full, or its actual membership once the tuner signals no more trials
    are coming (`on_no_more_trials`) — partial runs still prune instead of
    degrading to random search."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        max_t: int = 81,
        reduction_factor: float = 3,
    ):
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        self._bracket_budgets = [
            int(max_t * self.eta ** -k) or 1 for k in range(s_max + 1)
        ]
        self._bracket_capacity = [
            math.ceil((s_max + 1) / (k + 1)) * int(self.eta ** k)
            for k in range(s_max + 1)
        ]
        self._fill_order = list(range(s_max, -1, -1))  # aggressive first
        self._assign: Dict[Any, int] = {}  # trial_id -> bracket
        self._counts: Dict[int, int] = defaultdict(int)
        self._exhausted = False
        # bracket -> milestone -> {trial_id: score}
        self._rungs: Dict[int, Dict[int, Dict[Any, float]]] = defaultdict(
            lambda: defaultdict(dict)
        )
        self._stopped: set = set()
        self._done: set = set()  # completed/errored — will never report again

    def on_trial_add(self, trial):
        if trial.trial_id in self._assign:
            return
        for k in self._fill_order:
            if self._counts[k] < self._bracket_capacity[k]:
                self._assign[trial.trial_id] = k
                self._counts[k] += 1
                return
        # All brackets full: start a new cycle in the most aggressive one
        # (extra entrants join its later rungs; capacities still gate
        # resolution, so over-full rungs resolve at capacity).
        k = self._fill_order[0]
        self._assign[trial.trial_id] = k
        self._counts[k] += 1

    def on_no_more_trials(self):
        """The searcher is exhausted: brackets are as full as they will ever
        get — resolve any rung whose whole current membership has reported."""
        self._exhausted = True
        for bracket in list(self._rungs):
            for milestone in list(self._rungs[bracket]):
                self._maybe_resolve(bracket, milestone)

    def _population(self, bracket: int) -> Optional[int]:
        cap = self._bracket_capacity[bracket]
        assigned = self._counts[bracket]
        if assigned >= cap:
            return cap
        if self._exhausted:
            return max(1, assigned)
        return None  # still filling — wait

    def _maybe_resolve(self, bracket: int, milestone: int):
        rung = self._rungs[bracket][milestone]
        population = self._population(bracket)
        if population is None:
            return
        # Members that completed/were stopped WITHOUT reporting this rung can
        # never fill it — don't wait for them.
        absent = sum(
            1
            for tid, b in self._assign.items()
            if b == bracket
            and tid not in rung
            and (tid in self._done or tid in self._stopped)
        )
        if len(rung) < max(1, population - absent):
            return
        live = {tid: sc for tid, sc in rung.items() if tid not in self._stopped}
        keep = max(1, int(len(rung) / self.eta))
        ranked = sorted(live, key=live.get, reverse=True)
        for tid in ranked[keep:]:
            self._stopped.add(tid)

    def _bracket_of(self, trial) -> int:
        self.on_trial_add(trial)  # direct-driven schedulers (tests) lack add
        return self._assign[trial.trial_id]

    def _milestones(self, bracket: int) -> List[int]:
        out = []
        t = self._bracket_budgets[bracket]
        while t < self.max_t:
            out.append(int(t))
            t *= self.eta
        return out

    def on_trial_result(self, trial, result):
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if trial.trial_id in self._stopped:
            return STOP
        if t >= self.max_t:
            return STOP
        bracket = self._bracket_of(trial)
        # `t >= milestone`, recorded once per (trial, rung): reporting
        # cadences that step past the exact milestone still register.
        for milestone in self._milestones(bracket):
            if t >= milestone:
                rung = self._rungs[bracket][milestone]
                if trial.trial_id not in rung:
                    rung[trial.trial_id] = score
                    self._maybe_resolve(bracket, milestone)
                    if trial.trial_id in self._stopped:
                        return STOP
                else:
                    rung[trial.trial_id] = max(rung[trial.trial_id], score)
        return CONTINUE

    def on_trial_complete(self, trial, result):
        # A finished/errored trial can no longer report later rungs — mark it
        # absent so survivors' rungs still resolve without it.
        self._done.add(trial.trial_id)
        bracket = self._assign.get(trial.trial_id)
        if bracket is not None:
            for milestone in self._milestones(bracket):
                self._maybe_resolve(bracket, milestone)


class MedianStoppingRule(TrialScheduler):
    def __init__(self, time_attr: str = "training_iteration", grace_period: int = 1,
                 min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._best: Dict[Any, float] = {}

    def on_trial_result(self, trial, result):
        score = self._score(result)
        t = result.get(self.time_attr, 0)
        if score is None:
            return CONTINUE
        prev = self._best.get(trial.trial_id)
        self._best[trial.trial_id] = max(score, prev) if prev is not None else score
        if t < self.grace_period or len(self._best) < self.min_samples:
            return CONTINUE
        others = [v for k, v in self._best.items() if k != trial.trial_id]
        if not others:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        return STOP if self._best[trial.trial_id] < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: `schedulers/pbt.py`): every `perturbation_interval`
    the controller asks whether a trial should exploit a better one; the
    controller performs the checkpoint copy + restart, this class decides."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._scores: Dict[Any, float] = {}
        self._last_perturb: Dict[Any, int] = {}
        self._rng = random.Random(seed)

    def on_trial_result(self, trial, result):
        score = self._score(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        return CONTINUE

    def should_perturb(self, trial, result) -> bool:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return False
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        if trial.trial_id in bottom:
            self._last_perturb[trial.trial_id] = t
            return True
        return False

    def exploit_target(self, trial):
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1], reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        top = [tid for tid, _ in ranked[:k] if tid != trial.trial_id]
        return self._rng.choice(top) if top else None

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search_space import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or key not in new:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(new[key], (int, float)):
                    new[key] = type(new[key])(new[key] * factor)
        return new
