"""Search algorithms (reference: `python/ray/tune/search/`).

BasicVariantGenerator is the default (grid × random sampling). OptunaSearch /
HyperOptSearch adapt external libraries when installed (gated imports — the
environment may not carry them)."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from .search_space import Domain, resolve_grid, sample_variant


class Searcher:
    def set_objective(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]]):
        pass


class BasicVariantGenerator(Searcher):
    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed=None):
        self.rng = random.Random(seed)
        self._queue: List[Dict[str, Any]] = []
        for variant in resolve_grid(param_space):
            for _ in range(num_samples):
                self._queue.append(sample_variant(variant, self.rng))

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id):
        if not self._queue:
            return None
        return self._queue.pop(0)


# Sentinel: the searcher is THROTTLED (not exhausted) — the controller
# should try again later instead of concluding no more trials exist.
BUSY = object()


class TPESearch(Searcher):
    """NATIVE tree-structured Parzen estimator — an original implementation,
    NOT the optuna integration (use OptunaSearch when optuna is installed).
    Reference analog in spirit: `tune/search/optuna` (TPE sampler) /
    `tune/search/hyperopt`. Completed trials split into good/bad by the γ
    quantile; candidates sample near good observations and are scored by a
    kernel-density ratio good(x)/bad(x)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 16,
                 seed=None, gamma: float = 0.25, n_candidates: int = 24,
                 min_observations: int = 6):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.min_observations = min_observations
        self._suggested = 0
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._scores: List[tuple] = []  # (score, config)

    def _numeric_keys(self):
        from .search_space import LogUniform, RandInt, Uniform

        return {
            k: v for k, v in self.param_space.items()
            if isinstance(v, (Uniform, LogUniform, RandInt))
        }

    def _random_config(self) -> Dict[str, Any]:
        return sample_variant(
            next(iter(resolve_grid(self.param_space))), self.rng
        )

    def suggest(self, trial_id):
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        if len(self._scores) < self.min_observations:
            config = self._random_config()
        else:
            config = self._tpe_config()
        self._configs[trial_id] = config
        return config

    def _tpe_config(self) -> Dict[str, Any]:
        import math

        ordered = sorted(self._scores, key=lambda t: -t[0])
        n_good = max(1, int(len(ordered) * self.gamma))
        good = [c for _, c in ordered[:n_good]]
        bad = [c for _, c in ordered[n_good:]] or good

        numeric = self._numeric_keys()

        def density(configs, key, x, scale):
            # Gaussian KDE with a fixed bandwidth fraction of the range.
            s = 0.0
            for c in configs:
                v = c.get(key)
                if v is None:
                    continue
                d = (float(x) - float(v)) / scale
                s += math.exp(-0.5 * d * d)
            return s / max(len(configs), 1) + 1e-12

        best, best_ratio = None, float("-inf")
        for _ in range(self.n_candidates):
            # Sample near a random good observation (explore via mutation).
            base = dict(self.rng.choice(good))
            cand = self._random_config()
            for k, dom in numeric.items():
                lo, hi = _domain_bounds(dom)
                scale = max((hi - lo) * 0.2, 1e-9)
                center = float(base.get(k, cand[k]))
                v = self.rng.gauss(center, scale)
                cand[k] = _domain_clip(dom, v)
            ratio = 1.0
            for k, dom in numeric.items():
                lo, hi = _domain_bounds(dom)
                scale = max((hi - lo) * 0.25, 1e-9)
                ratio *= density(good, k, cand[k], scale) / density(
                    bad, k, cand[k], scale
                )
            if ratio > best_ratio:
                best, best_ratio = cand, ratio
        return best or self._random_config()

    def on_trial_complete(self, trial_id, result):
        config = self._configs.pop(trial_id, None)
        if config is None or result is None:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        self._scores.append((score, config))


def _domain_bounds(dom):
    from .search_space import LogUniform, RandInt, Uniform

    if isinstance(dom, Uniform):
        return dom.low, dom.high
    if isinstance(dom, RandInt):
        return dom.low, dom.high - 1
    if isinstance(dom, LogUniform):
        import math

        return math.exp(dom.log_low), math.exp(dom.log_high)
    raise TypeError(type(dom))


def _domain_clip(dom, v):
    from .search_space import RandInt

    lo, hi = _domain_bounds(dom)
    v = min(max(v, lo), hi)
    return int(round(v)) if isinstance(dom, RandInt) else v


class BOHBSearch(TPESearch):
    """BOHB-STYLE bracketed search, natively implemented (reference analog:
    `tune/search/bohb/` + `schedulers/hb_bohb.py`): pair this searcher with
    the HyperBandScheduler — the model (TPE) learns from every rung report,
    not only terminal results, so later brackets start from informed
    configs. This is an original implementation, not the `hpbandster`
    integration."""

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        """Rung-level observations feed the model early (BOHB's core idea)."""
        config = self._configs.get(trial_id)
        if config is None or result is None:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        self._scores.append((score, config))

    def on_trial_complete(self, trial_id, result):
        # The final report already reached the model via on_trial_result —
        # scoring it again would double-weight terminal observations.
        self._configs.pop(trial_id, None)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from any searcher (reference:
    `tune/search/concurrency_limiter.py`). While at the cap, suggest()
    answers BUSY — throttled, not exhausted."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_objective(self, metric, mode):
        super().set_objective(metric, mode)
        self.searcher.set_objective(metric, mode)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return BUSY
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not BUSY:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        hook = getattr(self.searcher, "on_trial_result", None)
        if hook is not None:
            hook(trial_id, result)

    def on_trial_complete(self, trial_id, result):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class OptunaSearch(Searcher):
    """Adapter over optuna TPE (reference: `search/optuna/optuna_search.py`).
    Requires optuna (not bundled); for a dependency-free alternative use the
    native TPESearch."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 8, seed=None):
        import optuna  # gated: raises if not installed

        self._optuna = optuna
        self.param_space = param_space
        self.num_samples = num_samples
        self._study = optuna.create_study(
            direction="maximize",
            sampler=optuna.samplers.TPESampler(seed=seed),
        )
        self._trials: Dict[str, Any] = {}
        self._suggested = 0

    def suggest(self, trial_id):
        from .search_space import Choice, LogUniform, RandInt, Uniform

        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        otrial = self._study.ask()
        config = {}
        for k, v in self.param_space.items():
            if isinstance(v, Uniform):
                config[k] = otrial.suggest_float(k, v.low, v.high)
            elif isinstance(v, LogUniform):
                import math

                config[k] = otrial.suggest_float(
                    k, math.exp(v.log_low), math.exp(v.log_high), log=True
                )
            elif isinstance(v, RandInt):
                config[k] = otrial.suggest_int(k, v.low, v.high - 1)
            elif isinstance(v, Choice):
                config[k] = otrial.suggest_categorical(k, v.categories)
            elif isinstance(v, Domain):
                config[k] = v.sample(random.Random())
            else:
                config[k] = v
        self._trials[trial_id] = otrial
        return config

    def on_trial_complete(self, trial_id, result):
        otrial = self._trials.pop(trial_id, None)
        if otrial is None or result is None:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        self._study.tell(otrial, score)
