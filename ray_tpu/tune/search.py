"""Search algorithms (reference: `python/ray/tune/search/`).

BasicVariantGenerator is the default (grid × random sampling). OptunaSearch /
HyperOptSearch adapt external libraries when installed (gated imports — the
environment may not carry them)."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from .search_space import Domain, resolve_grid, sample_variant


class Searcher:
    def set_objective(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]]):
        pass


class BasicVariantGenerator(Searcher):
    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed=None):
        self.rng = random.Random(seed)
        self._queue: List[Dict[str, Any]] = []
        for variant in resolve_grid(param_space):
            for _ in range(num_samples):
                self._queue.append(sample_variant(variant, self.rng))

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id):
        if not self._queue:
            return None
        return self._queue.pop(0)


class OptunaSearch(Searcher):
    """Adapter over optuna TPE (reference: `search/optuna/optuna_search.py`)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 8, seed=None):
        import optuna  # gated: raises if not installed

        self._optuna = optuna
        self.param_space = param_space
        self.num_samples = num_samples
        self._study = optuna.create_study(
            direction="maximize",
            sampler=optuna.samplers.TPESampler(seed=seed),
        )
        self._trials: Dict[str, Any] = {}
        self._suggested = 0

    def suggest(self, trial_id):
        from .search_space import Choice, LogUniform, RandInt, Uniform

        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        otrial = self._study.ask()
        config = {}
        for k, v in self.param_space.items():
            if isinstance(v, Uniform):
                config[k] = otrial.suggest_float(k, v.low, v.high)
            elif isinstance(v, LogUniform):
                import math

                config[k] = otrial.suggest_float(
                    k, math.exp(v.log_low), math.exp(v.log_high), log=True
                )
            elif isinstance(v, RandInt):
                config[k] = otrial.suggest_int(k, v.low, v.high - 1)
            elif isinstance(v, Choice):
                config[k] = otrial.suggest_categorical(k, v.categories)
            elif isinstance(v, Domain):
                config[k] = v.sample(random.Random())
            else:
                config[k] = v
        self._trials[trial_id] = otrial
        return config

    def on_trial_complete(self, trial_id, result):
        otrial = self._trials.pop(trial_id, None)
        if otrial is None or result is None:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        self._study.tell(otrial, score)
