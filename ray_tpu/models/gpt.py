"""GPT model family (GPT-2 / GPT-J / Llama-style), TPU-first.

Design (vs the reference's torch models driven through Train/DeepSpeed —
`release/air_examples/gptj_deepspeed_finetuning`):
  * pure-functional pytree params — no module framework between the math and
    pjit; shardings come from `ShardingRules` logical dims.
  * ONE stacked layer pytree + `lax.scan` over the layer axis → constant
    compile time in depth, XLA pipelines the remat.
  * attention is pluggable: "flash" (Pallas), "ring" (sp-axis sequence
    parallel), "ulysses", "ref" — long context is a config flag, not a fork.
  * bf16 params/activations, f32 optimizer state & softmax stats.

Flagship configs: `gpt2_*` (LayerNorm/GELU/learned-pos), `gptj_6b`
(parallel block + rotary), `llama_7b`-style (RMSNorm/SwiGLU/rotary).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import apply_rope, flash_attention, layernorm, ring_attention, rmsnorm, rope_frequencies
from ..ops.attention import attention_reference, ulysses_attention
from ..parallel.mesh import ShardingRules


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # padded to a multiple of 128 for the MXU
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_head: int = 64
    d_mlp: int = 3072
    max_seq: int = 1024
    # Architecture knobs.
    norm: str = "layernorm"          # layernorm | rmsnorm
    activation: str = "gelu"         # gelu | swiglu
    pos: str = "learned"             # learned | rotary
    rotary_dim: int = 64
    parallel_block: bool = False     # GPT-J: attn and mlp in parallel
    tie_embeddings: bool = True
    # Mixture-of-Experts (expert parallelism over the ep mesh axis).
    mlp_type: str = "dense"          # dense | moe
    moe_experts: int = 8
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Execution knobs.
    dtype: Any = jnp.bfloat16
    attn_impl: str = "flash"         # flash | ring | ulysses | ref
    remat: bool = True
    # None (save nothing) | "dots" | "attn" (save flash attention's out+lse
    # so backward never re-runs the VPU-bound forward kernel — the costliest
    # recompute per the r4 profile; +~32 MB/layer at B=12,S=1024).
    remat_policy: Optional[str] = None
    sp_axis: str = "sp"

    @property
    def moe_config(self):
        from ..ops.moe import MoEConfig

        return MoEConfig(
            num_experts=self.moe_experts,
            top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            d_model=self.d_model,
            d_ff=self.d_mlp,
            aux_loss_weight=self.moe_aux_weight,
            activation=self.activation,
            dtype=self.dtype,
        )

    @property
    def n_params(self) -> int:
        E, L, F, V, Hd = self.d_model, self.n_layers, self.d_mlp, self.vocab_size, self.n_heads * self.d_head
        if self.mlp_type == "moe":
            n_mats = 3 if self.activation == "swiglu" else 2
            mlp_params = self.moe_experts * n_mats * E * F + E * self.moe_experts
        else:
            mlp_params = (2 if self.activation == "swiglu" else 1) * E * F + F * E
        per_layer = E * 3 * Hd + Hd * E + mlp_params
        per_layer += 2 * E  # norms
        total = L * per_layer + V * E + (0 if self.tie_embeddings else E * V)
        if self.pos == "learned":
            total += self.max_seq * E
        return total

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token: 6N + attention term (12·L·E·S·(S/S) approx)."""
        return 6.0 * self.n_params + 12.0 * self.n_layers * self.d_model * seq_len


# Canonical configs ---------------------------------------------------------
def gpt2_small(**kw):
    return GPTConfig(**{**dict(n_layers=12, d_model=768, n_heads=12, d_mlp=3072), **kw})


def gpt2_medium(**kw):
    return GPTConfig(**{**dict(n_layers=24, d_model=1024, n_heads=16, d_mlp=4096), **kw})


def gpt2_large(**kw):
    return GPTConfig(**{**dict(n_layers=36, d_model=1280, n_heads=20, d_mlp=5120), **kw})


def gpt2_xl(**kw):
    return GPTConfig(**{**dict(n_layers=48, d_model=1600, n_heads=25, d_mlp=6400), **kw})


def gptj_6b(**kw):
    return GPTConfig(
        **{
            **dict(
                n_layers=28,
                d_model=4096,
                n_heads=16,
                d_head=256,
                d_mlp=16384,
                vocab_size=50432,
                pos="rotary",
                rotary_dim=64,
                parallel_block=True,
                tie_embeddings=False,
                max_seq=2048,
            ),
            **kw,
        }
    )


def llama_7b(**kw):
    return GPTConfig(
        **{
            **dict(
                n_layers=32,
                d_model=4096,
                n_heads=32,
                d_head=128,
                d_mlp=11008,
                vocab_size=32000,
                norm="rmsnorm",
                activation="swiglu",
                pos="rotary",
                rotary_dim=128,
                tie_embeddings=False,
                max_seq=2048,
            ),
            **kw,
        }
    )


CONFIGS = {
    "gpt2-small": gpt2_small,
    "gpt2-medium": gpt2_medium,
    "gpt2-large": gpt2_large,
    "gptj-6b": gptj_6b,
    "llama-7b": llama_7b,
}


# ------------------------------------------------------------------- params
def param_logical_dims(cfg: GPTConfig) -> Dict[str, Tuple[Optional[str], ...]]:
    """Logical dims per parameter — feed through ShardingRules for shardings."""
    dims = {
        "tok_embed": ("vocab", "embed"),
        "ln_f_w": ("embed_act",),
        "ln_f_b": ("embed_act",),
        "w_qkv": ("layers", "embed", None, "heads", "head_dim"),
        "b_qkv": ("layers", None, "heads", "head_dim"),
        "w_o": ("layers", "heads", "head_dim", "embed"),
        "b_o": ("layers", "embed_act"),
        "ln1_w": ("layers", "embed_act"),
        "ln1_b": ("layers", "embed_act"),
    }
    if cfg.mlp_type == "moe":
        dims["moe_router"] = ("layers", "embed", "experts")
        dims["moe_w_in"] = ("layers", "experts", "embed", "mlp")
        dims["moe_w_out"] = ("layers", "experts", "mlp", "embed")
        if cfg.activation == "swiglu":
            dims["moe_w_gate"] = ("layers", "experts", "embed", "mlp")
    else:
        dims["w_in"] = ("layers", "embed", "mlp")
        dims["b_in"] = ("layers", "mlp_act")
        dims["w_out"] = ("layers", "mlp", "embed")
        dims["b_out"] = ("layers", "embed_act")
        if cfg.activation == "swiglu":
            dims["w_gate"] = ("layers", "embed", "mlp")
    if not cfg.parallel_block:
        dims["ln2_w"] = ("layers", "embed_act")
        dims["ln2_b"] = ("layers", "embed_act")
    if cfg.pos == "learned":
        dims["pos_embed"] = (None, "embed")
    if not cfg.tie_embeddings:
        dims["lm_head"] = ("embed", "vocab")
    return dims


def init_params(rng, cfg: GPTConfig) -> Dict[str, jnp.ndarray]:
    E, L, F, V = cfg.d_model, cfg.n_layers, cfg.d_mlp, cfg.vocab_size
    H, Dh = cfg.n_heads, cfg.d_head
    k = jax.random.split(rng, 16)
    std = 0.02
    resid_std = std / math.sqrt(2 * L)
    # Master params live in f32 (optimizer precision); forward casts each
    # layer's weights to cfg.dtype (bf16) as the scan touches it.
    dt = jnp.float32

    def n(key, shape, s=std):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

    params = {
        "tok_embed": n(k[0], (V, E)),
        "ln_f_w": jnp.ones((E,), dt),
        "ln_f_b": jnp.zeros((E,), dt),
        "w_qkv": n(k[1], (L, E, 3, H, Dh)),
        "b_qkv": jnp.zeros((L, 3, H, Dh), dt),
        "w_o": n(k[2], (L, H, Dh, E), resid_std),
        "b_o": jnp.zeros((L, E), dt),
        "ln1_w": jnp.ones((L, E), dt),
        "ln1_b": jnp.zeros((L, E), dt),
    }
    if cfg.mlp_type == "moe":
        X = cfg.moe_experts
        params["moe_router"] = n(k[3], (L, E, X))
        params["moe_w_in"] = n(k[4], (L, X, E, F))
        params["moe_w_out"] = n(k[5], (L, X, F, E), resid_std)
        if cfg.activation == "swiglu":
            params["moe_w_gate"] = n(k[8], (L, X, E, F))
    else:
        params["w_in"] = n(k[3], (L, E, F))
        params["b_in"] = jnp.zeros((L, F), dt)
        params["w_out"] = n(k[4], (L, F, E), resid_std)
        params["b_out"] = jnp.zeros((L, E), dt)
        if cfg.activation == "swiglu":
            params["w_gate"] = n(k[5], (L, E, F))
    if not cfg.parallel_block:
        params["ln2_w"] = jnp.ones((L, E), dt)
        params["ln2_b"] = jnp.zeros((L, E), dt)
    if cfg.pos == "learned":
        params["pos_embed"] = n(k[6], (cfg.max_seq, E))
    if not cfg.tie_embeddings:
        params["lm_head"] = n(k[7], (E, V))
    return params


def param_shardings(cfg: GPTConfig, mesh, rules: Optional[ShardingRules] = None):
    rules = rules or ShardingRules.default()
    dims = param_logical_dims(cfg)
    return {name: rules.sharding(mesh, *d) for name, d in dims.items()}


# ------------------------------------------------------------------ forward
def _norm(x, w, b, kind: str):
    if kind == "rmsnorm":
        return rmsnorm(x, w)
    return layernorm(x, w, b)


def _attention(cfg: GPTConfig, q, k, v, mesh=None):
    """Two integration modes for sequence parallelism:

    * mesh=None (manual SPMD): caller wrapped the whole forward in shard_map;
      axis names are already bound — call the collective impl directly.
    * mesh given (automatic/pjit): everything else auto-partitions; only the
      attention core drops into a nested shard_map over the mesh so the ring
      ppermutes ride the sp axis while XLA keeps handling dp/fsdp/tp.
    """
    if cfg.attn_impl in ("ring", "ulysses"):
        impl = ring_attention if cfg.attn_impl == "ring" else ulysses_attention
        impl = functools.partial(impl, axis=cfg.sp_axis, causal=True)
        if mesh is None:
            return impl(q, k, v)
        from jax.sharding import PartitionSpec as P

        from ..parallel.spmd import shard_fn

        spec = P(("dp", "fsdp"), "tp", cfg.sp_axis, None)
        fn = shard_fn(impl, mesh, in_specs=(spec,) * 3, out_specs=spec)
        return fn(q, k, v)
    if cfg.attn_impl == "ref":
        return attention_reference(q, k, v, causal=True)
    if cfg.remat and cfg.remat_policy == "attn":
        from ..ops.attention import flash_attention_with_stats

        # The stats variant's vjp names its residuals ("attn_out"/"attn_lse")
        # so the "attn" remat policy saves them instead of re-running the
        # forward kernel; lse exists only for that purpose.
        o, _ = flash_attention_with_stats(q, k, v, causal=True)
        return o
    return flash_attention(q, k, v, causal=True)


def _block(cfg: GPTConfig, rope_tables, mesh, x, layer_params, positions,
           return_kv: bool = False):
    """One transformer block; x: [B, S, E] in cfg.dtype. With return_kv the
    post-RoPE K/V ([B, H, S, Dh]) come back too — the prefill path stores
    them in the decode cache."""
    # Cast this layer's master weights to compute dtype (bf16 → MXU).
    p = jax.tree_util.tree_map(lambda a: a.astype(cfg.dtype), layer_params)
    B, S, E = x.shape
    H, Dh = cfg.n_heads, cfg.d_head

    h = _norm(x, p["ln1_w"], p["ln1_b"], cfg.norm)
    qkv = jnp.einsum("bse,ethd->btshd", h, p["w_qkv"]) + p["b_qkv"][:, None]
    q, k, v = (qkv[:, i].transpose(0, 2, 1, 3).reshape(B, H, S, Dh) for i in range(3))
    # qkv[:, i] is [B, S, H, Dh] -> [B, H, S, Dh]
    if cfg.pos == "rotary":
        cos, sin = rope_tables
        rd = min(cfg.rotary_dim, Dh)
        c, s = cos[positions], sin[positions]
        q = jnp.concatenate([apply_rope(q[..., :rd], c, s, None), q[..., rd:]], -1) \
            if rd < Dh else apply_rope(q, c, s, None)
        k = jnp.concatenate([apply_rope(k[..., :rd], c, s, None), k[..., rd:]], -1) \
            if rd < Dh else apply_rope(k, c, s, None)
    attn = _attention(cfg, q, k, v, mesh)  # [B, H, S, Dh]
    attn_out = jnp.einsum("bhsd,hde->bse", attn, p["w_o"]) + p["b_o"]

    if cfg.parallel_block:
        mlp_in = h  # GPT-J: same normed input feeds attn and mlp
    else:
        x = x + attn_out
        mlp_in = _norm(x, p["ln2_w"], p["ln2_b"], cfg.norm)

    aux = jnp.zeros((), jnp.float32)
    if cfg.mlp_type == "moe":
        from ..ops.moe import moe_forward

        moe_params = {
            "w_router": layer_params["moe_router"],  # router math stays f32
            "w_in": p["moe_w_in"],
            "w_out": p["moe_w_out"],
        }
        if cfg.activation == "swiglu":
            moe_params["w_gate"] = p["moe_w_gate"]
        mlp_out, aux = moe_forward(moe_params, mlp_in, cfg.moe_config)
    else:
        u = jnp.einsum("bse,ef->bsf", mlp_in, p["w_in"]) + p["b_in"]
        if cfg.activation == "swiglu":
            g = jnp.einsum("bse,ef->bsf", mlp_in, p["w_gate"])
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        mlp_out = jnp.einsum("bsf,fe->bse", u, p["w_out"]) + p["b_out"]

    out = x + attn_out + mlp_out if cfg.parallel_block else x + mlp_out
    if return_kv:
        return out, (aux, k, v)
    return out, aux


_LAYER_KEYS = (
    "w_qkv", "b_qkv", "w_o", "b_o", "w_in", "b_in", "w_out", "b_out",
    "ln1_w", "ln1_b", "ln2_w", "ln2_b", "w_gate",
    "moe_router", "moe_w_in", "moe_w_out", "moe_w_gate",
)


def global_positions(cfg: GPTConfig, local_seq: int):
    """Global token positions for this shard (manual-SPMD mode only). Under
    whole-model shard_map the function body sees only the LOCAL sequence
    chunk — positions must be offset by this device's sp-axis index or
    RoPE/learned-pos phases are wrong on every shard but the first."""
    if cfg.attn_impl in ("ring", "ulysses"):
        offset = jax.lax.axis_index(cfg.sp_axis) * local_seq
        return offset + jnp.arange(local_seq)
    return jnp.arange(local_seq)


def forward(params, tokens, cfg: GPTConfig, positions=None, mesh=None, return_aux=False):
    """tokens [B, S] → logits [B, S, V] (or (logits, moe_aux_loss) with
    return_aux=True).

    mesh=None → plain jit or caller-managed shard_map (manual SPMD).
    mesh given → automatic pjit partitioning with a nested shard_map around
    the attention core when cfg.attn_impl is ring/ulysses.
    """
    B, S = tokens.shape
    if positions is None:
        # In automatic (pjit) mode shapes are global — plain arange is right.
        positions = jnp.arange(S) if mesh is not None else global_positions(cfg, S)
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    if cfg.pos == "learned":
        x = x + params["pos_embed"][positions].astype(cfg.dtype)

    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)

    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    block = functools.partial(_block, cfg, rope_tables, mesh)
    if cfg.remat:
        block = jax.checkpoint(block, policy=_remat_policy(cfg))

    def scan_body(x, layer_params):
        x, aux = block(x, layer_params, positions)
        return x, aux

    x, aux_stack = jax.lax.scan(scan_body, x, layer_stack)

    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(cfg.dtype))
    if return_aux:
        return logits, aux_stack.sum()
    return logits


def _remat_policy(cfg: GPTConfig):
    if cfg.remat_policy not in (None, "dots", "attn"):
        raise ValueError(f"unknown remat_policy: {cfg.remat_policy!r}")
    if cfg.remat_policy == "attn" and cfg.attn_impl != "flash":
        # Only the flash path checkpoint_name's (attn_out, attn_lse);
        # elsewhere save_only_these_names would match nothing and silently
        # rematerialize everything — fail loudly instead.
        raise ValueError(
            "remat_policy='attn' saves flash-attention residuals; it requires "
            f"attn_impl='flash' (got {cfg.attn_impl!r})"
        )
    if cfg.remat_policy == "attn":
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse"
        )
    return (
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        if cfg.remat_policy == "dots"
        else jax.checkpoint_policies.nothing_saveable
    )


def _parse_batch(batch):
    """{"tokens": [B,S+1]} or {"inputs","targets"} → (inputs, targets, mask)."""
    if "inputs" in batch:
        return batch["inputs"], batch["targets"], batch.get("mask")
    tokens = batch["tokens"]
    mask = batch.get("mask")
    return tokens[:, :-1], tokens[:, 1:], (mask[:, 1:] if mask is not None else None)


def _ce_loss(logits, targets, mask):
    """Mean next-token cross-entropy (f32), optionally padding-masked."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        m = mask.astype(jnp.float32)
        return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)
    return -ll.mean()


def loss_fn(params, batch, cfg: GPTConfig, mesh=None):
    """batch: {"tokens": [B, S+1]} or {"inputs","targets"} → mean next-token
    cross-entropy (f32) + MoE aux."""
    inputs, targets, mask = _parse_batch(batch)
    logits, aux = forward(params, inputs, cfg, mesh=mesh, return_aux=True)
    return _ce_loss(logits, targets, mask) + aux


def make_train_step(cfg: GPTConfig, optimizer, mesh=None, loss=None) -> Callable:
    """Returns `step(state, batch) -> (state, metrics)`; jit at the call site
    with shardings (see ray_tpu.train.JaxTrainer / bench.py). `loss`
    overrides the loss callable (params, batch) -> scalar — the pipeline
    train step rides this hook."""
    if loss is None:
        def loss(params, batch):
            return loss_fn(params, batch, cfg, mesh)

    def step(state, batch):
        params, opt_state = state
        loss_val, grads = jax.value_and_grad(loss)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u.astype(p.dtype)), params, updates
        )
        gnorm = optax_global_norm(grads)
        return (params, opt_state), {"loss": loss_val, "grad_norm": gnorm}

    return step


def optax_global_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


# ------------------------------------------------------- pipeline parallelism
def split_stage_params(params, cfg: GPTConfig, num_stages: int):
    """Reshape the [L, ...] layer stack to [S, L/S, ...] (the `stage` logical
    dim — shard it P('pp') so each pp device holds exactly its stage's
    layers). Non-layer params (embeddings, final norm, head) stay as-is;
    they live outside the pipelined region."""
    if cfg.n_layers % num_stages != 0:
        raise ValueError(f"{cfg.n_layers} layers not divisible by {num_stages} stages")
    per = cfg.n_layers // num_stages
    out = {}
    for k, v in params.items():
        if k in _LAYER_KEYS:
            out[k] = v.reshape(num_stages, per, *v.shape[1:])
        else:
            out[k] = v
    return out


def merge_stage_params(params, cfg: GPTConfig):
    """Inverse of split_stage_params ([S, L/S, ...] -> [L, ...])."""
    out = {}
    for k, v in params.items():
        if k in _LAYER_KEYS:
            out[k] = v.reshape(v.shape[0] * v.shape[1], *v.shape[2:])
        else:
            out[k] = v
    return out


def extract_stage_params(
    params, cfg: GPTConfig, stage: int, num_stages: int,
    num_chunks: int = 1, chunk: int = 0,
):
    """The parameter subset chunk `chunk` of stage `stage` actually needs:
    its layer slice of the S*v virtual-stage split (virtual stage
    vs = chunk*S + stage), plus embeddings on the first virtual stage and
    the final norm + LM head on the last. With num_chunks=1 this is the
    classic per-host weight set for compiled-DAG pipelines; v>1 is the
    interleaved split where each host owns v non-contiguous layer groups
    (in-mesh GPipe keeps the full stacked params instead —
    `split_stage_params`). With tied embeddings, tok_embed lands on BOTH
    boundary virtual stages — the runners reconcile its gradient over the
    embedding bridge before the update."""
    pipeline = num_stages * num_chunks
    if cfg.n_layers % pipeline != 0:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {num_stages} stages "
            f"x {num_chunks} chunks"
        )
    if not 0 <= chunk < num_chunks:
        raise ValueError(f"chunk {chunk} out of range for {num_chunks} chunks")
    vs = chunk * num_stages + stage
    per = cfg.n_layers // pipeline
    out = {
        k: v[vs * per : (vs + 1) * per]
        for k, v in params.items()
        if k in _LAYER_KEYS
    }
    first, last = vs == 0, vs == pipeline - 1
    if first or (last and cfg.tie_embeddings):
        out["tok_embed"] = params["tok_embed"]
    if first and cfg.pos == "learned":
        out["pos_embed"] = params["pos_embed"]
    if last:
        out["ln_f_w"] = params["ln_f_w"]
        out["ln_f_b"] = params["ln_f_b"]
        if not cfg.tie_embeddings:
            out["lm_head"] = params["lm_head"]
    return out


def stage_forward(
    stage_params, inp, cfg: GPTConfig, *, first: bool, last: bool,
    positions=None, mesh=None,
):
    """One pipeline stage of `forward`: embed if `first`, this stage's layer
    slice, final norm + head if `last`. `inp` is tokens [B, S] on the first
    stage, activations [B, S, E] (cfg.dtype — what the compiled-DAG edge
    ships between hosts) otherwise. Returns (output, moe_aux_sum)."""
    if first:
        _, S = inp.shape
        if positions is None:
            positions = jnp.arange(S) if mesh is not None else global_positions(cfg, S)
        x = stage_params["tok_embed"][inp].astype(cfg.dtype)
        if cfg.pos == "learned":
            x = x + stage_params["pos_embed"][positions].astype(cfg.dtype)
    else:
        x = inp.astype(cfg.dtype)
        S = x.shape[1]
        if positions is None:
            positions = jnp.arange(S) if mesh is not None else global_positions(cfg, S)

    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)

    layer_stack = {k: stage_params[k] for k in _LAYER_KEYS if k in stage_params}
    block = functools.partial(_block, cfg, rope_tables, mesh)
    if cfg.remat:
        block = jax.checkpoint(block, policy=_remat_policy(cfg))

    def scan_body(x, layer_params):
        x, aux = block(x, layer_params, positions)
        return x, aux

    x, aux_stack = jax.lax.scan(scan_body, x, layer_stack)
    if not last:
        return x, aux_stack.sum()
    x = _norm(x, stage_params["ln_f_w"], stage_params["ln_f_b"], cfg.norm)
    head = (
        stage_params["tok_embed"].T if cfg.tie_embeddings else stage_params["lm_head"]
    )
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(cfg.dtype))
    return logits, aux_stack.sum()


def check_mpmd_partitionable(
    cfg: GPTConfig, num_stages: int, num_chunks: int = 1
) -> None:
    """Constraints of the MPMD stage split (each stage a SEPARATE jit
    program on its own gang actor — `ray_tpu.train.mpmd`):

    * layers must divide evenly into the S*v virtual stages (same rule as
      in-mesh GPipe for v=1);
    * interleaving (num_chunks > 1) needs num_stages > 1 — chunk-to-chunk
      edges on a single stage would be self-loops;
    * tied embeddings are ALLOWED: tok_embed lives on both boundary
      virtual stages and the runners allreduce its gradient over a
      dedicated first/last-stage bridge channel before the update
      (the Megatron embedding allreduce), keeping the two copies
      bit-identical;
    * MoE is not composed yet: the router aux loss is stage-local and the
      reported loss would silently omit upstream stages' aux terms.
    """
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_chunks < 1:
        raise ValueError(f"num_chunks must be >= 1, got {num_chunks}")
    if num_chunks > 1 and num_stages == 1:
        raise ValueError(
            "interleaved MPMD (num_chunks > 1) needs num_stages > 1"
        )
    if cfg.n_layers % (num_stages * num_chunks) != 0:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {num_stages} stages "
            f"x {num_chunks} chunks"
        )
    if cfg.mlp_type == "moe":
        raise NotImplementedError(
            "MPMD stages do not carry the MoE aux loss across hosts yet"
        )


def make_mpmd_stage_fns(
    cfg: GPTConfig, stage: int, num_stages: int,
    num_chunks: int = 1, chunk: int = 0,
) -> Dict[str, Callable]:
    """Pure per-chunk training functions for the MPMD pipeline (arXiv
    2412.14374 shape: stages as separate jit programs, the host-side 1F1B
    schedule moving activations/grads between them; num_chunks > 1 is the
    interleaved split where this call builds ONE of the stage's v chunk
    programs — virtual stage chunk*S + stage).

    Returned callables (jit them at the call site; all take the chunk's
    param subset from `extract_stage_params`):

    * ``fwd(params, x) -> y`` — forward only. x is tokens [B, S] on the
      first virtual stage, activations [B, S, E] elsewhere; y is the
      activation this chunk ships downstream (logits on the last).
    * non-last chunks: ``fwd_bwd(params, x, gy) -> (param_grads, gx)`` —
      backward via jax.vjp with the forward RECOMPUTED from the saved
      chunk input (activation recomputation: the 1F1B runner stores only
      each in-flight microbatch's chunk INPUT, the memory shape that makes
      deep pipelines fit). On the first virtual stage gx is None (tokens).
    * last chunk: ``loss_bwd(params, x, targets, mask) -> (loss,
      param_grads, gx)`` — next-token CE in f32, grads wrt params and the
      incoming activation.
    """
    check_mpmd_partitionable(cfg, num_stages, num_chunks)
    vs = chunk * num_stages + stage
    first, last = vs == 0, vs == num_stages * num_chunks - 1

    def _fwd(p, x):
        y, _aux = stage_forward(p, x, cfg, first=first, last=last)
        return y

    fns: Dict[str, Callable] = {"fwd": _fwd}
    if last:
        def _loss(p, x, targets, mask):
            logits, _aux = stage_forward(p, x, cfg, first=first, last=True)
            return _ce_loss(logits, targets, mask)

        if first:  # S == 1 degenerate pipeline: input is tokens, no gx
            def loss_bwd(p, x, targets, mask=None):
                loss, gp = jax.value_and_grad(_loss)(p, x, targets, mask)
                return loss, gp, None
        else:
            def loss_bwd(p, x, targets, mask=None):
                loss, (gp, gx) = jax.value_and_grad(_loss, argnums=(0, 1))(
                    p, x, targets, mask
                )
                return loss, gp, gx

        fns["loss_bwd"] = loss_bwd
    else:
        if first:
            def fwd_bwd(p, x, gy):
                # Tokens are integers — differentiate wrt params only.
                _y, vjp = jax.vjp(lambda p_: _fwd(p_, x), p)
                (gp,) = vjp(gy)
                return gp, None
        else:
            def fwd_bwd(p, x, gy):
                _y, vjp = jax.vjp(_fwd, p, x)
                gp, gx = vjp(gy)
                return gp, gx

        fns["fwd_bwd"] = fwd_bwd
    return fns


def pipeline_stage_shardings(cfg: GPTConfig, mesh, rules: Optional[ShardingRules] = None):
    """Param shardings for the stage-split layout: layer arrays gain a
    leading `stage` dim (→ pp); the rest match param_shardings."""
    rules = rules or ShardingRules.default()
    dims = param_logical_dims(cfg)
    out = {}
    for name, d in dims.items():
        if name in _LAYER_KEYS:
            assert d[0] == "layers"
            out[name] = rules.sharding(mesh, "stage", *d)
        else:
            out[name] = rules.sharding(mesh, *d)
    return out


def pipeline_loss_fn(
    params,
    batch,
    cfg: GPTConfig,
    mesh,
    num_microbatches: int,
):
    """GPipe loss: the transformer stack runs inside a shard_map manual over
    ONLY the `pp` axis — microbatches flow stage→stage via ppermute while the
    compiler keeps auto-partitioning each stage's math over dp/fsdp/tp/sp
    (the `axis_names` subset-manual mode). Embedding/head/loss stay outside
    the pipelined region in ordinary pjit land.

    Reference gap being closed: Ray has NO pipeline schedule (SURVEY §2.6 —
    compiled-DAG channels are substrate only); here GPipe's backward emerges
    from jax AD transposing the forward scan. `params` is the stage-split
    layout from split_stage_params.

    Limitations: manual sp attention (ring/ulysses) cannot nest inside the
    pp-manual region — those impls are rejected; with "ref"/"flash" the
    compiler still auto-partitions attention over sp (all-gather based). The
    MoE aux loss is averaged per microbatch (≈ the full-batch value).
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.spmd import shard_fn

    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            f"attn_impl={cfg.attn_impl!r} needs its own manual sp axis and "
            "cannot nest inside the pp-manual pipeline region; use 'flash' "
            "or 'ref' (XLA auto-partitions those over sp)."
        )
    S_pp = mesh.shape["pp"]
    M = num_microbatches
    inputs, targets, mask = _parse_batch(batch)
    B, S_len = inputs.shape
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by num_microbatches {M}")
    positions = jnp.arange(S_len)

    x = params["tok_embed"][inputs].astype(cfg.dtype)
    if cfg.pos == "learned":
        x = x + params["pos_embed"][positions].astype(cfg.dtype)
    # The pipeline input crosses the shard_map boundary in f32: AD transposes
    # its stage-0 broadcast into a psum, and bf16 psums crash the partitioner
    # in subset-manual mode (see the matching forward-path comment below).
    xm = x.reshape(M, B // M, S_len, x.shape[-1]).astype(jnp.float32)

    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)

    stage_stack = {k: params[k] for k in _LAYER_KEYS if k in params}
    block = functools.partial(_block, cfg, rope_tables, None)
    if cfg.remat:
        block = jax.checkpoint(block, policy=_remat_policy(cfg))

    def stage_fn(stage_params, act):
        def body(h, layer_params):
            h, aux = block(h, layer_params, positions)
            return h, aux

        act, aux_stack = lax.scan(body, act, stage_params)
        return act, aux_stack.sum()

    def per_stage(stacked, xm):
        local = jax.tree_util.tree_map(lambda p: p[0], stacked)  # my stage
        s = lax.axis_index("pp")
        is_first = s == 0
        is_last = s == S_pp - 1
        fwd_perm = [(i, i + 1) for i in range(S_pp - 1)]
        mb_shape = xm.shape[1:]
        outs0 = jnp.zeros((M,) + mb_shape, cfg.dtype)
        act0 = jnp.zeros(mb_shape, cfg.dtype)

        def tick(carry, t):
            act_in, outs, aux_acc = carry
            x_t = lax.dynamic_index_in_dim(xm, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(is_first, x_t.astype(cfg.dtype), act_in)
            y, aux = stage_fn(local, inp)
            # Stage s holds real data only for ticks s <= t < s + M; bubble
            # ticks chew zeros and must not pollute the MoE aux loss.
            valid = jnp.logical_and(t >= s, t < s + M).astype(jnp.float32)
            aux_acc = aux_acc + aux * valid
            write_idx = jnp.clip(t - (S_pp - 1), 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(outs, y, write_idx, 0)
            outs = jnp.where(jnp.logical_and(is_last, t >= S_pp - 1), updated, outs)
            act_next = lax.ppermute(y, "pp", fwd_perm)
            return (act_next, outs, aux_acc), None

        (_, outs, aux_acc), _ = lax.scan(
            tick, (act0, outs0, jnp.zeros((), jnp.float32)), jnp.arange(M + S_pp - 1)
        )
        # psum in f32: a bf16 psum under subset-manual shard_map crashes the
        # SPMD partitioner ("Invalid binary instruction opcode copy").
        masked = jnp.where(is_last, outs, jnp.zeros_like(outs)).astype(jnp.float32)
        return lax.psum(masked, "pp"), lax.psum(aux_acc, "pp") / M

    gpipe = shard_fn(
        per_stage,
        mesh,
        in_specs=(P("pp"), P()),
        out_specs=(P(), P()),
        manual_axes=frozenset({"pp"}),
    )
    y, aux = gpipe(stage_stack, xm)
    y = y.astype(cfg.dtype).reshape(B, S_len, -1)

    h = _norm(y, params["ln_f_w"].astype(cfg.dtype), params["ln_f_b"].astype(cfg.dtype), cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bse,ev->bsv", h, head.astype(cfg.dtype))
    return _ce_loss(logits, targets, mask) + aux


def make_pipeline_train_step(
    cfg: GPTConfig, optimizer, mesh, num_microbatches: int
) -> Callable:
    """`step(state, batch) -> (state, metrics)` with the GPipe pipeline
    inside one jit program (pp × dp/fsdp/tp composition)."""
    return make_train_step(
        cfg,
        optimizer,
        mesh,
        loss=lambda params, batch: pipeline_loss_fn(
            params, batch, cfg, mesh, num_microbatches
        ),
    )


# ---------------------------------------------------------------- generation
# KV-cache autoregressive inference (reference analog: the Serve LLM
# deployments the reference runs through vLLM/transformers — here decode is
# a first-class device-side loop: prefill fills the cache in one forward,
# then `lax.scan` advances one token per step entirely on-device, so a
# generation of N tokens is ONE dispatch, not N host round-trips — which is
# what the axon tunnel's ~100 ms RTT would otherwise cost per token).


def init_cache(cfg: GPTConfig, batch: int, max_seq: Optional[int] = None):
    """Decode cache: stacked per-layer post-RoPE K/V + current length."""
    M = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, cfg.n_heads, M, cfg.d_head)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def prefill(params, tokens, cfg: GPTConfig, cache):
    """Run the prompt [B, S0] through the model, filling cache[:, :, :, :S0].

    Returns (last_logits [B, V] f32, cache). Prompts are fixed-length
    (left-pad upstream for ragged batches). No remat (inference)."""
    B, S = tokens.shape
    positions = jnp.arange(S)
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    if cfg.pos == "learned":
        x = x + params["pos_embed"][positions].astype(cfg.dtype)
    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)
    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    icfg = dataclasses.replace(cfg, remat=False, remat_policy=None)

    def scan_body(x, layer_params):
        x, (aux, k, v) = _block(
            icfg, rope_tables, None, x, layer_params, positions, return_kv=True
        )
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(scan_body, x, layer_stack)  # [L, B, H, S, Dh]

    M = cache["k"].shape[3]
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
        ),
        "len": jnp.asarray(S, jnp.int32),
    }
    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("be,ev->bv", x[:, -1], head.astype(cfg.dtype))
    return logits.astype(jnp.float32), cache


def decode_step(params, token, cache, cfg: GPTConfig):
    """One autoregressive step: token [B] int32 → (logits [B, V] f32, cache).

    Attention is a plain masked dot against the cache — at S=1 the MXU
    matmuls are [B,H,1,D]x[B,H,M,D]; flash brings nothing and Pallas grid
    overhead would dominate."""
    if cfg.mlp_type == "moe":
        raise NotImplementedError("decode_step does not support MoE yet")
    B = token.shape[0]
    pos = cache["len"]                       # scalar int32
    x = params["tok_embed"][token][:, None].astype(cfg.dtype)  # [B, 1, E]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][pos][None, None].astype(cfg.dtype)
    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)
    M = cache["k"].shape[3]
    scale = 1.0 / math.sqrt(cfg.d_head)
    H, Dh = cfg.n_heads, cfg.d_head
    cols = jnp.arange(M)

    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    def scan_body(x, inp):
        layer_params, ck, cv = inp
        p = jax.tree_util.tree_map(lambda a: a.astype(cfg.dtype), layer_params)
        h = _norm(x, p["ln1_w"], p["ln1_b"], cfg.norm)
        qkv = jnp.einsum("bse,ethd->btshd", h, p["w_qkv"]) + p["b_qkv"][:, None]
        q, k, v = (
            qkv[:, i].transpose(0, 2, 1, 3).reshape(B, H, 1, Dh) for i in range(3)
        )
        if cfg.pos == "rotary":
            cos, sin = rope_tables
            rd = min(cfg.rotary_dim, Dh)
            c, s = cos[pos][None], sin[pos][None]  # [1, rd/2]
            q = jnp.concatenate([apply_rope(q[..., :rd], c, s, None), q[..., rd:]], -1) \
                if rd < Dh else apply_rope(q, c, s, None)
            k = jnp.concatenate([apply_rope(k[..., :rd], c, s, None), k[..., rd:]], -1) \
                if rd < Dh else apply_rope(k, c, s, None)
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, pos, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, pos, 0))
        scores = jnp.einsum(
            "bhsd,bhtd->bhst", q, ck, preferred_element_type=jnp.float32
        ) * scale                                      # [B, H, 1, M]
        scores = jnp.where(cols[None, None, None, :] <= pos, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhst,bhtd->bhsd", probs.astype(cv.dtype), cv)
        attn_out = jnp.einsum("bhsd,hde->bse", attn, p["w_o"]) + p["b_o"]

        if cfg.parallel_block:
            mlp_in = h
        else:
            x = x + attn_out
            mlp_in = _norm(x, p["ln2_w"], p["ln2_b"], cfg.norm)
        u = jnp.einsum("bse,ef->bsf", mlp_in, p["w_in"]) + p["b_in"]
        if cfg.activation == "swiglu":
            g = jnp.einsum("bse,ef->bsf", mlp_in, p["w_gate"])
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        mlp_out = jnp.einsum("bsf,fe->bse", u, p["w_out"]) + p["b_out"]
        out = x + attn_out + mlp_out if cfg.parallel_block else x + mlp_out
        return out, (ck, cv)

    x, (ks, vs) = jax.lax.scan(scan_body, x, (layer_stack, cache["k"], cache["v"]))
    cache = {"k": ks, "v": vs, "len": pos + 1}
    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("be,ev->bv", x[:, -1], head.astype(cfg.dtype))
    return logits.astype(jnp.float32), cache


# --------------------------------------------------- paged KV-cache decode
# Block-table cache layout for the continuous-batching engine
# (`ray_tpu.serve.engine`): the KV cache is a pool of fixed-size token
# blocks [L, NB, H, BS, Dh]; each sequence owns an ordered block table and
# token position p lives at (table[p // BS], p % BS). Unlike `init_cache`'s
# dense [L, B, H, M, Dh] layout, sequences of wildly different lengths
# share one physical pool with no per-sequence max_seq reservation — the
# memory model that makes iteration-level admission worth doing.
# Block 0 is the engine's null block: padding lanes in bucketed batches
# point their tables at it so their writes land somewhere harmless.


def init_paged_cache(cfg: GPTConfig, num_blocks: int, block_size: int):
    """Physical paged KV pool: {"k","v"} of [L, NB, H, BS, Dh] in cfg.dtype."""
    shape = (cfg.n_layers, num_blocks, cfg.n_heads, block_size, cfg.d_head)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _rope_rotate(x, c, s):
    """Half-split rotation with caller-broadcast (cos, sin) — the per-lane
    positions of a paged decode batch don't fit apply_rope's leading-dim
    broadcast."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1).astype(x.dtype)


def _rope_qk(cfg: GPTConfig, q, k, rope_tables, positions):
    """RoPE for [B, H, 1, Dh] q/k at per-lane integer positions [B]."""
    cos, sin = rope_tables
    rd = min(cfg.rotary_dim, cfg.d_head)
    c = cos[positions][:, None, None, :]  # [B, 1, 1, rd/2]
    s = sin[positions][:, None, None, :]
    if rd < cfg.d_head:
        q = jnp.concatenate([_rope_rotate(q[..., :rd], c, s), q[..., rd:]], -1)
        k = jnp.concatenate([_rope_rotate(k[..., :rd], c, s), k[..., rd:]], -1)
        return q, k
    return _rope_rotate(q, c, s), _rope_rotate(k, c, s)


def prefill_paged(params, tokens, real_len, pos_offset, block_table, kv,
                  cfg: GPTConfig):
    """Prompt prefill into the paged cache, one CHUNK of one sequence per
    call (chunked prefill: a long prompt lands a slice per engine step so
    decode streams keep emitting between slices).

    tokens [1, Sp] right-padded to the shape bucket holds
    prompt[pos_offset : pos_offset + real_len]; `real_len` / `pos_offset`
    are traced scalars (one compiled program per (Sp, W) bucket pair covers
    every chunk length and offset); `block_table` [W] int32 maps the
    sequence's blocks. Each layer scatters the chunk's K/V to its (block,
    offset) slots FIRST, then attends over the gathered table history —
    prefix-cache hits and earlier chunks' KV below `pos_offset` are read
    from the cache, never recomputed, and a monolithic prefill is just the
    pos_offset=0 chunk covering the whole prompt. K/V of padded positions
    scatter to the null block. Returns (next-token logits [V] f32 at global
    position pos_offset + real_len - 1, kv) — only meaningful on the FINAL
    chunk of a prompt.
    """
    if cfg.mlp_type == "moe":
        raise NotImplementedError("paged decode does not support MoE yet")
    _, Sp = tokens.shape
    BS = kv["k"].shape[3]
    W = block_table.shape[0]
    M = W * BS
    H, Dh = cfg.n_heads, cfg.d_head
    scale = 1.0 / math.sqrt(cfg.d_head)
    rel = jnp.arange(Sp)
    positions = pos_offset + rel                 # global token positions [Sp]
    x = params["tok_embed"][tokens].astype(cfg.dtype)  # [1, Sp, E]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][positions].astype(cfg.dtype)
    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)
    valid = rel < real_len
    phys = jnp.where(valid, block_table[jnp.minimum(positions // BS, W - 1)], 0)
    off = positions % BS
    cols = jnp.arange(M)
    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    def scan_body(x, inp):
        layer_params, kk, vv = inp  # kk/vv: [NB, H, BS, Dh]
        p = jax.tree_util.tree_map(lambda a: a.astype(cfg.dtype), layer_params)
        h = _norm(x, p["ln1_w"], p["ln1_b"], cfg.norm)
        qkv = jnp.einsum("bse,ethd->btshd", h, p["w_qkv"]) + p["b_qkv"][:, None]
        q, k, v = (
            qkv[:, i].transpose(0, 2, 1, 3).reshape(1, H, Sp, Dh)
            for i in range(3)
        )
        if cfg.pos == "rotary":
            cos, sin = rope_tables
            rd = min(cfg.rotary_dim, Dh)
            c, s = cos[positions], sin[positions]
            q = jnp.concatenate(
                [apply_rope(q[..., :rd], c, s, None), q[..., rd:]], -1
            ) if rd < Dh else apply_rope(q, c, s, None)
            k = jnp.concatenate(
                [apply_rope(k[..., :rd], c, s, None), k[..., rd:]], -1
            ) if rd < Dh else apply_rope(k, c, s, None)
        # Scatter the chunk's K/V to each position's (block, offset) slot,
        # then gather the WHOLE table history — cached prefix, earlier
        # chunks, and this chunk all come back through one path.
        kk = kk.at[phys, :, off].set(k[0].transpose(1, 0, 2).astype(kk.dtype))
        vv = vv.at[phys, :, off].set(v[0].transpose(1, 0, 2).astype(vv.dtype))
        gk = kk[block_table].transpose(1, 0, 2, 3).reshape(H, M, Dh)
        gv = vv[block_table].transpose(1, 0, 2, 3).reshape(H, M, Dh)
        scores = jnp.einsum(
            "hsd,htd->hst", q[0], gk, preferred_element_type=jnp.float32
        ) * scale                                    # [H, Sp, M]
        scores = jnp.where(
            cols[None, None, :] <= positions[None, :, None], scores, -1e30
        )
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("hst,htd->hsd", probs.astype(gv.dtype), gv)
        attn_out = jnp.einsum("bhsd,hde->bse", attn[None], p["w_o"]) + p["b_o"]

        if cfg.parallel_block:
            mlp_in = h
        else:
            x = x + attn_out
            mlp_in = _norm(x, p["ln2_w"], p["ln2_b"], cfg.norm)
        u = jnp.einsum("bse,ef->bsf", mlp_in, p["w_in"]) + p["b_in"]
        if cfg.activation == "swiglu":
            g = jnp.einsum("bse,ef->bsf", mlp_in, p["w_gate"])
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        mlp_out = jnp.einsum("bsf,fe->bse", u, p["w_out"]) + p["b_out"]
        out = x + attn_out + mlp_out if cfg.parallel_block else x + mlp_out
        return out, (kk, vv)

    x, (ks, vs) = jax.lax.scan(scan_body, x, (layer_stack, kv["k"], kv["v"]))
    kv = {"k": ks, "v": vs}
    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    h = x[0, jnp.maximum(real_len - 1, 0)]  # [E] — last REAL chunk position
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("e,ev->v", h, head.astype(cfg.dtype))
    return logits.astype(jnp.float32), kv


def decode_step_paged(params, token, positions, block_tables, kv, cfg: GPTConfig):
    """One iteration-level decode step over the paged cache.

    token [B] int32 — each lane's current token (written at `positions[b]`,
    attending to its own history 0..positions[b]); block_tables [B, W]
    int32. Lanes are independent sequences at unrelated positions — the
    continuous batch. Returns (logits [B, V] f32, kv). Padding lanes
    (block table = null block, position 0) produce garbage logits the
    engine discards.
    """
    if cfg.mlp_type == "moe":
        raise NotImplementedError("paged decode does not support MoE yet")
    B = token.shape[0]
    W = block_tables.shape[1]
    BS = kv["k"].shape[3]
    M = W * BS
    H, Dh = cfg.n_heads, cfg.d_head
    scale = 1.0 / math.sqrt(cfg.d_head)
    x = params["tok_embed"][token][:, None].astype(cfg.dtype)  # [B, 1, E]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][positions][:, None].astype(cfg.dtype)
    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)
    phys = jnp.take_along_axis(
        block_tables, (positions // BS)[:, None], axis=1
    )[:, 0]                                            # [B] physical block
    off = positions % BS
    cols = jnp.arange(M)
    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    def scan_body(x, inp):
        layer_params, kk, vv = inp  # kk/vv: [NB, H, BS, Dh]
        p = jax.tree_util.tree_map(lambda a: a.astype(cfg.dtype), layer_params)
        h = _norm(x, p["ln1_w"], p["ln1_b"], cfg.norm)
        qkv = jnp.einsum("bse,ethd->btshd", h, p["w_qkv"]) + p["b_qkv"][:, None]
        q, k, v = (
            qkv[:, i].transpose(0, 2, 1, 3).reshape(B, H, 1, Dh) for i in range(3)
        )
        if cfg.pos == "rotary":
            q, k = _rope_qk(cfg, q, k, rope_tables, positions)
        # Scatter this step's K/V to each lane's (block, offset) slot.
        kk = kk.at[phys, :, off].set(k[:, :, 0].astype(kk.dtype))
        vv = vv.at[phys, :, off].set(v[:, :, 0].astype(vv.dtype))
        # Gather each lane's history: [B, W, H, BS, Dh] -> [B, H, W*BS, Dh].
        gk = kk[block_tables].transpose(0, 2, 1, 3, 4).reshape(B, H, M, Dh)
        gv = vv[block_tables].transpose(0, 2, 1, 3, 4).reshape(B, H, M, Dh)
        scores = jnp.einsum(
            "bhsd,bhtd->bhst", q, gk, preferred_element_type=jnp.float32
        ) * scale                                       # [B, H, 1, M]
        scores = jnp.where(
            cols[None, None, None, :] <= positions[:, None, None, None],
            scores, -1e30,
        )
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhst,bhtd->bhsd", probs.astype(gv.dtype), gv)
        attn_out = jnp.einsum("bhsd,hde->bse", attn, p["w_o"]) + p["b_o"]

        if cfg.parallel_block:
            mlp_in = h
        else:
            x = x + attn_out
            mlp_in = _norm(x, p["ln2_w"], p["ln2_b"], cfg.norm)
        u = jnp.einsum("bse,ef->bsf", mlp_in, p["w_in"]) + p["b_in"]
        if cfg.activation == "swiglu":
            g = jnp.einsum("bse,ef->bsf", mlp_in, p["w_gate"])
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        mlp_out = jnp.einsum("bsf,fe->bse", u, p["w_out"]) + p["b_out"]
        out = x + attn_out + mlp_out if cfg.parallel_block else x + mlp_out
        return out, (kk, vv)

    x, (ks, vs) = jax.lax.scan(scan_body, x, (layer_stack, kv["k"], kv["v"]))
    kv = {"k": ks, "v": vs}
    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("be,ev->bv", x[:, -1], head.astype(cfg.dtype))
    return logits.astype(jnp.float32), kv


def verify_step_paged(params, tokens, positions, valid_len, block_tables, kv,
                      cfg: GPTConfig):
    """Speculative-decode verify: score k draft tokens (plus the lane's
    current token) in ONE forward over the paged cache.

    tokens [B, K1] int32 — lane b's token j sits at global position
    `positions[b] + j` (j=0 is the last emitted token whose KV has not
    landed yet, j>=1 are draft proposals); `valid_len` [B] int32 is the
    per-lane count of real tokens (<= K1; 0 for padding lanes — the K/V of
    slots at or past it scatter to the null block so a short draft can
    never clobber a neighbouring block through index clamping);
    block_tables [B, W] int32 as in `decode_step_paged`. Each layer
    scatters all K1 tokens' K/V first, then attends causally (query j sees
    history 0..positions[b]+j), so logits[b, j] is EXACTLY what a
    sequential `decode_step_paged` would produce after accepting drafts
    0..j-1 — the greedy accept rule (longest matching draft prefix + one
    corrective/bonus token) therefore reproduces non-speculative greedy
    decode token-for-token. Returns (logits [B, K1, V] f32, kv).
    """
    if cfg.mlp_type == "moe":
        raise NotImplementedError("paged decode does not support MoE yet")
    B, K1 = tokens.shape
    W = block_tables.shape[1]
    BS = kv["k"].shape[3]
    M = W * BS
    H, Dh = cfg.n_heads, cfg.d_head
    scale = 1.0 / math.sqrt(cfg.d_head)
    pos = positions[:, None] + jnp.arange(K1)[None, :]          # [B, K1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)           # [B, K1, E]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][pos].astype(cfg.dtype)
    rope_tables = None
    if cfg.pos == "rotary":
        rd = min(cfg.rotary_dim, cfg.d_head)
        rope_tables = rope_frequencies(rd, cfg.max_seq, dtype=jnp.float32)
    valid = jnp.arange(K1)[None, :] < valid_len[:, None]        # [B, K1]
    phys = jnp.where(
        valid,
        jnp.take_along_axis(
            block_tables, jnp.minimum(pos // BS, W - 1), axis=1
        ),
        0,
    )
    off = pos % BS
    cols = jnp.arange(M)
    layer_stack = {k: params[k] for k in _LAYER_KEYS if k in params}

    def scan_body(x, inp):
        layer_params, kk, vv = inp  # kk/vv: [NB, H, BS, Dh]
        p = jax.tree_util.tree_map(lambda a: a.astype(cfg.dtype), layer_params)
        h = _norm(x, p["ln1_w"], p["ln1_b"], cfg.norm)
        qkv = jnp.einsum("bse,ethd->btshd", h, p["w_qkv"]) + p["b_qkv"][:, None]
        q, k, v = (
            qkv[:, i].transpose(0, 2, 1, 3).reshape(B, H, K1, Dh)
            for i in range(3)
        )
        if cfg.pos == "rotary":
            cos, sin = rope_tables
            rd = min(cfg.rotary_dim, Dh)
            c = cos[pos][:, None]                               # [B, 1, K1, rd/2]
            s = sin[pos][:, None]
            if rd < Dh:
                q = jnp.concatenate(
                    [_rope_rotate(q[..., :rd], c, s), q[..., rd:]], -1
                )
                k = jnp.concatenate(
                    [_rope_rotate(k[..., :rd], c, s), k[..., rd:]], -1
                )
            else:
                q, k = _rope_rotate(q, c, s), _rope_rotate(k, c, s)
        # Scatter every lane's K1 tokens to their (block, offset) slots,
        # then gather each lane's table history — the drafts' own keys come
        # back through the same path, so query j attends drafts 0..j.
        kk = kk.at[phys, :, off].set(k.transpose(0, 2, 1, 3).astype(kk.dtype))
        vv = vv.at[phys, :, off].set(v.transpose(0, 2, 1, 3).astype(vv.dtype))
        gk = kk[block_tables].transpose(0, 2, 1, 3, 4).reshape(B, H, M, Dh)
        gv = vv[block_tables].transpose(0, 2, 1, 3, 4).reshape(B, H, M, Dh)
        scores = jnp.einsum(
            "bhsd,bhtd->bhst", q, gk, preferred_element_type=jnp.float32
        ) * scale                                               # [B, H, K1, M]
        scores = jnp.where(
            cols[None, None, None, :] <= pos[:, None, :, None], scores, -1e30
        )
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhst,bhtd->bhsd", probs.astype(gv.dtype), gv)
        attn_out = jnp.einsum("bhsd,hde->bse", attn, p["w_o"]) + p["b_o"]

        if cfg.parallel_block:
            mlp_in = h
        else:
            x = x + attn_out
            mlp_in = _norm(x, p["ln2_w"], p["ln2_b"], cfg.norm)
        u = jnp.einsum("bse,ef->bsf", mlp_in, p["w_in"]) + p["b_in"]
        if cfg.activation == "swiglu":
            g = jnp.einsum("bse,ef->bsf", mlp_in, p["w_gate"])
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        mlp_out = jnp.einsum("bsf,fe->bse", u, p["w_out"]) + p["b_out"]
        out = x + attn_out + mlp_out if cfg.parallel_block else x + mlp_out
        return out, (kk, vv)

    x, (ks, vs) = jax.lax.scan(scan_body, x, (layer_stack, kv["k"], kv["v"]))
    kv = {"k": ks, "v": vs}
    x = _norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bke,ev->bkv", x, head.astype(cfg.dtype))
    return logits.astype(jnp.float32), kv


def make_generate(cfg: GPTConfig, max_new_tokens: int, temperature: float = 0.0):
    """Returns jittable `gen(params, prompt [B, S0], rng) -> tokens
    [B, max_new_tokens]`: prefill + a device-side `lax.scan` decode loop —
    one dispatch per GENERATION, not per token."""

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, -1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature).astype(jnp.int32)

    def gen(params, prompt, rng):
        B, S0 = prompt.shape
        cache = init_cache(cfg, B, S0 + max_new_tokens)
        logits, cache = prefill(params, prompt, cfg, cache)
        rng, k0 = jax.random.split(rng)
        first = sample(logits, k0)

        def step(carry, key):
            token, cache = carry
            logits, cache = decode_step(params, token, cache, cfg)
            nxt = sample(logits, key)
            return (nxt, cache), token

        keys = jax.random.split(rng, max_new_tokens - 1) if max_new_tokens > 1 \
            else jnp.zeros((0, 2), jnp.uint32)
        (last, _), toks = jax.lax.scan(step, (first, cache), keys)
        return jnp.concatenate([toks.T, last[:, None]], axis=1)

    return gen
