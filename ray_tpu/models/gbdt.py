"""Gradient-boosted decision trees, JAX-native.

Reference analog: `python/ray/train/gbdt_trainer.py` + the xgboost/lightgbm
trainers built on it — the reference delegates the math to external C++
boosters. TPU redesign: a histogram booster written directly in JAX so the
whole training round is one jitted program of dense, fixed-shape ops
(XLA-friendly): features are quantile-binned to uint8 once on the host;
each round computes gradients, builds [node, feature, bin] histograms with
`segment_sum`, picks splits by vectorized gain, and routes samples — no
per-node Python, no dynamic shapes. Trees are complete binary trees in
array form (feature/threshold/leaf-value per node), so prediction is D
vectorized gathers.

Supports squared-error regression and binary logistic classification —
the two objectives the reference's release tests gate
(`release/train_tests/xgboost_lightgbm`).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class GBDTParams:
    objective: str = "squared_error"   # squared_error | binary_logistic
    num_boost_round: int = 50
    max_depth: int = 4
    learning_rate: float = 0.1
    reg_lambda: float = 1.0            # L2 on leaf values
    gamma: float = 0.0                 # min split gain
    min_child_weight: float = 1.0      # min hessian sum per child
    max_bins: int = 256                # uint8 binning
    base_score: float = 0.0


def quantile_bins(X: np.ndarray, max_bins: int = 256) -> np.ndarray:
    """Per-feature quantile cut points [F, max_bins-1] (host-side, once)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    return np.quantile(X, qs, axis=0).T.astype(np.float32)  # [F, B-1]


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """float features -> uint8 bin indices via the stored cut points."""
    out = np.empty(X.shape, np.uint8)
    for f in range(X.shape[1]):
        out[:, f] = np.searchsorted(edges[f], X[:, f], side="left")
    return out


def _grad_hess(objective: str, pred, y):
    if objective == "squared_error":
        return pred - y, jnp.ones_like(pred)
    if objective in ("binary_logistic", "binary:logistic"):
        p = jax.nn.sigmoid(pred)
        return p - y, p * (1.0 - p)
    raise ValueError(f"unknown objective {objective!r}")


@functools.partial(jax.jit, static_argnames=("depth", "n_bins"))
def _grow_tree(bins, g, h, depth: int, n_bins: int, reg_lambda, gamma,
               min_child_weight):
    """One tree on binned features. bins [N, F] uint8; g,h [N] f32.
    Returns (feature, threshold, leaf_value, is_leaf) arrays sized for the
    complete binary tree of `depth` (2^(depth+1)-1 nodes)."""
    N, F = bins.shape
    n_nodes_total = 2 ** (depth + 1) - 1
    feat = jnp.zeros((n_nodes_total,), jnp.int32)
    thresh = jnp.zeros((n_nodes_total,), jnp.int32)
    is_leaf = jnp.ones((n_nodes_total,), bool)
    node_g = jnp.zeros((n_nodes_total,), jnp.float32)
    node_h = jnp.zeros((n_nodes_total,), jnp.float32)
    node_g = node_g.at[0].set(g.sum())
    node_h = node_h.at[0].set(h.sum())

    assign = jnp.zeros((N,), jnp.int32)  # tree-node index per sample
    f_idx = jnp.arange(F, dtype=jnp.int32)[None, :]

    for d in range(depth):
        first, n_level = 2 ** d - 1, 2 ** d
        # Histograms for this level: local node id × feature × bin.
        local = assign - first  # [-] samples not at this level get clamped
        at_level = (assign >= first) & (assign < first + n_level)
        local = jnp.clip(local, 0, n_level - 1)
        seg = (
            local[:, None] * (F * n_bins)
            + f_idx * n_bins
            + bins.astype(jnp.int32)
        )  # [N, F]
        w = at_level.astype(jnp.float32)[:, None]
        num_seg = n_level * F * n_bins
        hist_g = jax.ops.segment_sum(
            jnp.broadcast_to(g[:, None] * w, (N, F)).ravel(),
            seg.ravel(), num_segments=num_seg,
        ).reshape(n_level, F, n_bins)
        hist_h = jax.ops.segment_sum(
            jnp.broadcast_to(h[:, None] * w, (N, F)).ravel(),
            seg.ravel(), num_segments=num_seg,
        ).reshape(n_level, F, n_bins)

        # Split gain for "left = bin <= b": cumulative stats over bins.
        GL = jnp.cumsum(hist_g, axis=-1)
        HL = jnp.cumsum(hist_h, axis=-1)
        G = GL[..., -1:]
        H = HL[..., -1:]
        GR, HR = G - GL, H - HL
        gain = 0.5 * (
            GL**2 / (HL + reg_lambda)
            + GR**2 / (HR + reg_lambda)
            - G**2 / (H + reg_lambda)
        ) - gamma
        valid = (HL >= min_child_weight) & (HR >= min_child_weight)
        gain = jnp.where(valid, gain, -jnp.inf)
        flat = gain.reshape(n_level, F * n_bins)
        best = flat.argmax(axis=-1)
        best_gain = jnp.take_along_axis(flat, best[:, None], -1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > 0.0

        node_ids = first + jnp.arange(n_level)
        feat = feat.at[node_ids].set(jnp.where(do_split, best_f, 0))
        thresh = thresh.at[node_ids].set(jnp.where(do_split, best_b, 0))
        is_leaf = is_leaf.at[node_ids].set(~do_split)

        # Child aggregates (for leaf values at the last level).
        lg = jnp.take_along_axis(
            GL.reshape(n_level, -1), (best_f * n_bins + best_b)[:, None], -1
        )[:, 0]
        lh = jnp.take_along_axis(
            HL.reshape(n_level, -1), (best_f * n_bins + best_b)[:, None], -1
        )[:, 0]
        left_ids, right_ids = 2 * node_ids + 1, 2 * node_ids + 2
        node_g = node_g.at[left_ids].set(lg).at[right_ids].set(
            node_g[node_ids] - lg
        )
        node_h = node_h.at[left_ids].set(lh).at[right_ids].set(
            node_h[node_ids] - lh
        )

        # Route samples whose node split.
        nf = feat[assign]
        nb = thresh[assign]
        sample_bin = jnp.take_along_axis(
            bins.astype(jnp.int32), nf[:, None], axis=1
        )[:, 0]
        split_here = at_level & ~is_leaf[assign]
        assign = jnp.where(
            split_here,
            jnp.where(sample_bin <= nb, 2 * assign + 1, 2 * assign + 2),
            assign,
        )

    leaf_value = -node_g / (node_h + reg_lambda)
    return feat, thresh, leaf_value.astype(jnp.float32), is_leaf


@functools.partial(jax.jit, static_argnames=("depth",))
def _predict_tree(bins, feat, thresh, leaf_value, is_leaf, depth: int):
    N = bins.shape[0]
    idx = jnp.zeros((N,), jnp.int32)
    for _ in range(depth):
        nf = feat[idx]
        nb = thresh[idx]
        sample_bin = jnp.take_along_axis(
            bins.astype(jnp.int32), nf[:, None], axis=1
        )[:, 0]
        nxt = jnp.where(sample_bin <= nb, 2 * idx + 1, 2 * idx + 2)
        idx = jnp.where(is_leaf[idx], idx, nxt)
    return leaf_value[idx]


@dataclass
class GradientBoostedTrees:
    """Fitted ensemble. `trees` holds stacked per-tree arrays."""

    params: GBDTParams
    edges: np.ndarray = None               # [F, max_bins-1] bin cut points
    trees: Dict[str, np.ndarray] = field(default_factory=dict)
    train_history: List[float] = field(default_factory=list)

    def fit(self, X: np.ndarray, y: np.ndarray,
            eval_every: int = 10) -> "GradientBoostedTrees":
        p = self.params
        if not 2 <= p.max_bins <= 256:
            # Bin indices live in uint8 — beyond 256 they'd silently wrap.
            raise ValueError(f"max_bins must be in [2, 256], got {p.max_bins}")
        X = np.asarray(X, np.float32)
        y = jnp.asarray(np.asarray(y, np.float32))
        self.edges = quantile_bins(X, p.max_bins)
        bins = jnp.asarray(apply_bins(X, self.edges))
        pred = jnp.full((X.shape[0],), p.base_score, jnp.float32)
        feats, threshs, leaves, leafmask = [], [], [], []
        for r in range(p.num_boost_round):
            g, h = _grad_hess(p.objective, pred, y)
            t = _grow_tree(
                bins, g, h, p.max_depth, p.max_bins,
                p.reg_lambda, p.gamma, p.min_child_weight,
            )
            pred = pred + p.learning_rate * _predict_tree(
                bins, *t, p.max_depth
            )
            feats.append(t[0]); threshs.append(t[1])
            leaves.append(t[2]); leafmask.append(t[3])
            if r % eval_every == 0 or r == p.num_boost_round - 1:
                self.train_history.append(float(self._loss(pred, y)))
        self.trees = {
            "feat": np.stack([np.asarray(a) for a in feats]),
            "thresh": np.stack([np.asarray(a) for a in threshs]),
            "leaf": np.stack([np.asarray(a) for a in leaves]),
            "is_leaf": np.stack([np.asarray(a) for a in leafmask]),
        }
        return self

    def _loss(self, pred, y):
        if self.params.objective == "squared_error":
            return jnp.mean((pred - y) ** 2)
        ll = jax.nn.log_sigmoid(pred) * y + jax.nn.log_sigmoid(-pred) * (1 - y)
        return -ll.mean()

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        bins = jnp.asarray(apply_bins(np.asarray(X, np.float32), self.edges))
        pred = jnp.full((X.shape[0],), self.params.base_score, jnp.float32)
        for i in range(self.trees["feat"].shape[0]):
            pred = pred + self.params.learning_rate * _predict_tree(
                bins,
                jnp.asarray(self.trees["feat"][i]),
                jnp.asarray(self.trees["thresh"][i]),
                jnp.asarray(self.trees["leaf"][i]),
                jnp.asarray(self.trees["is_leaf"][i]),
                self.params.max_depth,
            )
        return np.asarray(pred)

    def predict(self, X: np.ndarray) -> np.ndarray:
        raw = self.predict_raw(X)
        if self.params.objective == "squared_error":
            return raw
        return 1.0 / (1.0 + np.exp(-raw))  # probabilities

    # ------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return {
            "params": self.params.__dict__,
            "edges": self.edges,
            "trees": self.trees,
            "train_history": self.train_history,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "GradientBoostedTrees":
        m = cls(GBDTParams(**d["params"]))
        m.edges = d["edges"]
        m.trees = d["trees"]
        m.train_history = list(d.get("train_history", []))
        return m
