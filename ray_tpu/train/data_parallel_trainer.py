"""DataParallelTrainer — gang of workers running the same loop on data shards."""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, Optional

from .backend_executor import Backend, BackendExecutor
from .base_trainer import BaseTrainer
from .config import RunConfig, ScalingConfig
from .result import Result


class CollectiveBackend(Backend):
    """Sets up a host-plane collective group over the gang so workers can
    allreduce out-of-jit arrays (role of Gloo in the reference)."""

    def __init__(self, group_name: Optional[str] = None):
        self.group_name = group_name or f"train_{uuid.uuid4().hex[:8]}"
        self._started_once = False

    def on_start(self, worker_group, scaling):
        if self._started_once:
            # Gang RESTART: the rendezvous actor still holds the dead
            # incarnation's round state (partial refs, tombstones, stale
            # membership) — a re-formed gang joining it would desync. Kill
            # it; the new members' init_collective_group recreates a fresh
            # one under the same name (and the world size may have shrunk
            # within the elasticity band).
            from .. import collective

            try:
                collective.destroy_collective_group(self.group_name)
            except Exception:  # noqa: BLE001 — already gone
                pass
        self._started_once = True
        if len(worker_group) > 1:
            worker_group.setup_collective(self.group_name)


class DataParallelTrainer(BaseTrainer):
    """Reference analog: `python/ray/train/data_parallel_trainer.py`.

    `train_loop_per_worker(config)` runs on every worker; inside it use
    `ray_tpu.train.report/get_context/get_checkpoint`, the gang's collective
    group (`ray_tpu.collective`, group name in config["collective_group"]),
    and `get_dataset_shard` for per-worker data.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        backend: Optional[Backend] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint=None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.backend = backend or CollectiveBackend()

    def fit(self) -> Result:
        executor = BackendExecutor(
            self.backend,
            self.scaling_config,
            self.run_config,
            experiment_name=self.run_config.name or "train",
        )
        if self.resume_from_checkpoint is not None:
            executor._latest_checkpoint = self.resume_from_checkpoint
        if self.datasets:
            # Registered BEFORE start so gang restarts re-attach shards too.
            executor.set_datasets(self.datasets)
        # No explicit start(): run() performs the first start through the
        # same guarded path as restarts, so a member dying during the
        # INITIAL gang formation also consumes FailureConfig budget and
        # tears down the partial group instead of escaping fit().
        config = dict(self.train_loop_config)
        if isinstance(self.backend, CollectiveBackend):
            config.setdefault("collective_group", self.backend.group_name)
        try:
            result = executor.run(self.train_loop_per_worker, config)
        finally:
            executor.shutdown()
        return result
