"""BaseTrainer / DataParallelTrainer / JaxTrainer.

Reference analogs: `python/ray/train/base_trainer.py:579 fit`,
`data_parallel_trainer.py:432 training_loop`, `torch/config.py` backend.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, Optional

from .backend_executor import Backend, BackendExecutor
from .config import RunConfig, ScalingConfig
from .result import Result


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint=None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError
