"""Multi-host SPMD gang validation — one mesh spanning processes.

This is the executable proof of the framework's core promise: N host
processes, each owning a subset of devices, joined by
`jax.distributed.initialize` into ONE global mesh, running ONE compiled
train step whose collectives cross the process boundary.

Reference analog: the torch process-group path this replaces is e2e-tested
in the reference (`python/ray/train/torch/config.py:106,148` via
`python/ray/train/_internal/backend_executor.py:124`); here the gang is a
union `jax.sharding.Mesh` instead of a NCCL communicator.

`run_gang_step()` is deliberately process-count agnostic: the SAME function
runs single-process (8 local devices) or multi-process (2×4), and must
produce the same loss — that equivalence is what the tests assert.

Run as a module to join a gang from a fresh interpreter:

    python -m ray_tpu.train.gang_check <process_id> <num_processes> \
        <coordinator host:port> <devices_per_process>
"""

from __future__ import annotations

from typing import Dict


def run_gang_step() -> Dict[str, float]:
    """Build a dp×fsdp mesh over ALL global devices (local + remote), run a
    shard_map psum and one GPT train step, return scalars for comparison.

    Must be called after `jax.distributed.initialize` when spanning
    processes (`jax_utils.maybe_init_distributed`), or directly in a
    single-process run.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import GPTConfig, init_params, make_train_step, param_shardings
    from ray_tpu.parallel import MeshSpec, shard_fn

    n = jax.device_count()
    if n % 2:
        raise ValueError(f"gang check needs an even device count, got {n}")
    mesh = MeshSpec(dp=2, fsdp=n // 2).build(jax.devices())
    data_axes = ("dp", "fsdp")

    # 1) shard_map allreduce across the union mesh: device i holds value i,
    # the psum must see every process's shard (28.0 for n=8).
    per_dev = jax.jit(
        lambda: jnp.arange(float(n)),
        out_shardings=NamedSharding(mesh, P(data_axes)),
    )()
    total = jax.jit(
        shard_fn(
            lambda x: jax.lax.psum(jnp.sum(x), data_axes),
            mesh,
            in_specs=P(data_axes),
            out_specs=P(),
        )
    )(per_dev)
    psum = float(total)

    # 2) one GPT train step sharded dp×fsdp. Params/opt/batch are all
    # materialized INSIDE jit with explicit out_shardings — the standard
    # multi-host idiom (each process computes only its addressable shards).
    cfg = GPTConfig(
        vocab_size=512,
        n_layers=2,
        d_model=128,
        n_heads=4,
        d_head=32,
        d_mlp=256,
        max_seq=128,
        pos="rotary",
        rotary_dim=32,
        attn_impl="ref",
        remat=True,
    )
    shardings = param_shardings(cfg, mesh)
    params = jax.jit(
        lambda k: init_params(k, cfg), out_shardings=shardings
    )(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-3)
    opt_state = jax.jit(opt.init)(params)

    B = 2 * n
    tokens = jax.jit(
        lambda k: jax.random.randint(k, (B, cfg.max_seq + 1), 0, cfg.vocab_size),
        out_shardings=NamedSharding(mesh, P(data_axes, None)),
    )(jax.random.PRNGKey(1))

    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0,))
    state, metrics = step((params, opt_state), {"tokens": tokens})
    # Loss and grad_norm are fully replicated → every process can read them.
    loss = float(metrics["loss"])
    grad_norm = float(metrics["grad_norm"])
    assert loss == loss and loss > 0, f"bad gang loss {loss}"
    assert grad_norm > 0, "gang gradients are zero"
    return {
        "loss": loss,
        "grad_norm": grad_norm,
        "psum": psum,
        "n_global": float(n),
        "n_local": float(jax.local_device_count()),
    }


def spawn_gang(
    nprocs: int = 2,
    devices_per_proc: int = 4,
    timeout: float = 420.0,
    _bind_attempts: int = 3,
):
    """Spawn `nprocs` fresh interpreters that join one jax.distributed gang
    and each run `run_gang_step`; returns the parsed per-process results.

    Shared by `tests/test_multihost_gang.py` and
    `__graft_entry__._dryrun_multiprocess_gang` so the CLI protocol lives in
    one place. Stdout goes to temp files (not pipes) so a chatty worker can
    never wedge the gang on a full pipe, and every worker is killed on any
    failure path — a surviving sibling would otherwise sit in a collective
    waiting for its dead peer.

    Coordinator-port TOCTOU (ADVICE r5 #5): the port is picked bind-then-
    close, and another process can take it before worker 0's
    jax.distributed coordinator binds it. The socket is held open with
    SO_REUSEADDR until just before the workers launch (shrinks the window
    to microseconds), and a rendezvous failure that looks like a lost
    bind race retries the whole gang on a fresh port.
    """
    import json
    import os
    import socket
    import subprocess
    import sys
    import tempfile
    import time

    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    procs = []
    logs = []
    try:
        # Hold the reservation until the last instant: the coordinator
        # child binds with SO_REUSEADDR-compatible semantics only after
        # this close, so the race window is the exec latency, not the
        # whole test-collection interval.
        s.close()
        for pid in range(nprocs):
            log = tempfile.TemporaryFile(mode="w+")
            logs.append(log)
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.train.gang_check",
                     str(pid), str(nprocs), coord, str(devices_per_proc)],
                    stdout=log, stderr=subprocess.STDOUT, cwd=repo,
                )
            )
        deadline = time.monotonic() + timeout
        for p in procs:
            left = deadline - time.monotonic()
            p.wait(timeout=max(left, 1.0))
        outs = []
        for pid, (p, log) in enumerate(zip(procs, logs)):
            log.seek(0)
            out = log.read()
            if p.returncode != 0:
                lowered = out.lower()
                if _bind_attempts > 1 and (
                    "address already in use" in lowered
                    or "errno 98" in lowered
                    or "failed to bind" in lowered
                    or "bind address" in lowered
                ):
                    # Lost the coordinator-port race: kill the gang (the
                    # finally-block below) and retry on a fresh port.
                    for q in procs:
                        if q.poll() is None:
                            q.kill()
                    return spawn_gang(
                        nprocs, devices_per_proc, timeout,
                        _bind_attempts=_bind_attempts - 1,
                    )
                raise RuntimeError(f"gang worker {pid} failed:\n{out[-4000:]}")
            lines = [l for l in out.splitlines() if l.startswith("GANG_RESULT ")]
            if not lines:
                raise RuntimeError(f"no GANG_RESULT from worker {pid}:\n{out[-4000:]}")
            outs.append(json.loads(lines[-1][len("GANG_RESULT "):]))
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            if p.poll() is None:
                p.wait(timeout=10)
        for log in logs:
            log.close()


def _main() -> None:
    import json
    import os
    import sys

    pid, nprocs, coord, local = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        int(sys.argv[4]),
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local}"
    os.environ["RAY_TPU_JAX_COORDINATOR"] = coord
    os.environ["RAY_TPU_JAX_NUM_PROCESSES"] = str(nprocs)
    os.environ["RAY_TPU_JAX_PROCESS_ID"] = str(pid)

    import jax

    # The ambient sitecustomize pins the axon TPU platform at interpreter
    # start; redirect before the backend initializes (same dance as
    # tests/conftest.py and __graft_entry__._force_cpu_devices).
    jax.config.update("jax_platforms", "cpu")

    from ray_tpu.train.jax_trainer import jax_utils

    assert jax_utils.maybe_init_distributed(), "coordinator env missing"
    out = run_gang_step()
    print("GANG_RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    _main()
