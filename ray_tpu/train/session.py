"""In-loop training session API: report / get_context / get_checkpoint.

Reference analog: `python/ray/train/_internal/session.py` (`_TrainSession`,
`report` `:393,653`) — user code calls `ray_tpu.train.report(metrics,
checkpoint=...)` from inside `train_loop_per_worker`; the backend executor
polls results from the worker actors.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_name: str = ""
    trial_id: str = ""
    storage_path: str = ""
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    latest_checkpoint: Optional[Any] = None
    # Per-worker env (rank vars, jax coordinator). Kept here as well as in
    # os.environ because local-mode worker actors share one process — the
    # session copy is the authoritative per-worker view.
    env_vars: Dict[str, str] = field(default_factory=dict)

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id

    def get_storage(self) -> str:
        return self.storage_path


class _Session:
    def __init__(self, context: TrainContext):
        self.context = context
        self.results: "queue.Queue" = queue.Queue()
        # Cursor-readable copy of every report: a poll RESPONSE lost in
        # flight (the gang poll batch raising because a sibling died) must
        # not lose this worker's reports — the executor re-reads from its
        # last acknowledged index. Cursor polls implicitly ack (and trim)
        # everything below the requested index, so memory stays bounded by
        # the poll interval. The destructive queue stays for drain-style
        # consumers (tune's tuner); cursor mode discards it.
        self.history: list = []
        self.history_base = 0  # absolute index of history[0]
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # Lazily-created ElasticSession (train/elastic): async shard writer
        # + deterministic-resume state for this worker. Owned here so the
        # worker thread can flush it when the loop ends.
        self.elastic = None

    def report(self, metrics: Dict[str, Any], checkpoint=None):
        entry = {"metrics": dict(metrics), "checkpoint": checkpoint}
        self.history.append(entry)
        self.results.put(entry)


_session: Optional[_Session] = None
_session_lock = threading.Lock()
_thread_session = threading.local()


def init_session(context: TrainContext) -> _Session:
    global _session
    with _session_lock:
        _session = _Session(context)
        return _session


def bind_thread_session(session: _Session):
    """Bind a session to the current thread. Needed because (a) the user loop
    runs on its own thread inside the worker actor, and (b) in local mode
    multiple worker actors share one process, so a bare global would collide."""
    _thread_session.value = session


def get_session() -> Optional[_Session]:
    s = getattr(_thread_session, "value", None)
    return s if s is not None else _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


# ------------------------------------------------------------------ public
def report(metrics: Dict[str, Any], checkpoint=None):
    """Report metrics (+ optional Checkpoint) from the training loop."""
    s = get_session()
    if s is None:
        raise RuntimeError("report() called outside a training worker")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        return TrainContext()
    return s.context


def get_checkpoint():
    s = get_session()
    return s.context.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    s = get_session()
    if s is None:
        return None
    return s.context.dataset_shards.get(name)


def get_streaming_ingest(name: str = "train", *, batch_size: int = 256,
                         **kwargs):
    """This rank's dataset shard wrapped in a `StreamingIngest` — a bounded
    per-rank prefetch queue over the streaming pull plane, so epoch N+1's
    shard/preprocess/shuffle overlaps epoch N's steps (backpressure parks
    the producer when the trainer falls behind; docs/STREAMING_DATA.md).
    Callers own shutdown(): use ``with session.get_streaming_ingest(...)``
    around the step loop. None when the rank has no such shard."""
    shard = get_dataset_shard(name)
    if shard is None:
        return None
    from ..data.streaming import StreamingIngest

    return StreamingIngest(shard, batch_size, **kwargs)


def get_elastic_session():
    """The worker's ElasticSession (created on first use) — async sharded
    checkpointing + deterministic resume. See ray_tpu.train.elastic."""
    from .elastic import elastic_session

    return elastic_session()
