"""WorkerGroup — a gang of training worker actors.

Reference analog: `python/ray/train/_internal/worker_group.py:102` — N actors
created with per-worker resources, functions pushed to all workers. Gang
placement uses a STRICT_PACK/PACK placement group like slice gangs in the
reference's TPU pod scheduling (`_private/accelerators/tpu.py:199-313`).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

from .. import core
from ..core import api
from .session import TrainContext, get_session, init_session, shutdown_session


class TrainWorker:
    """Actor hosting one training worker (runs user fn on a thread so the
    actor stays responsive for result polling)."""

    def __init__(self, context_kwargs: Dict[str, Any]):
        self.context = TrainContext(**context_kwargs)
        self.session = init_session(self.context)
        self._thread: Optional[threading.Thread] = None
        self._collective: Optional[tuple] = None

    def set_env(self, env: Dict[str, str]):
        import os

        self.context.env_vars.update(env)
        os.environ.update(env)
        return True

    def setup_collective(self, world_size: int, rank: int, group_name: str):
        # Recorded only; the actual init happens on the loop thread in run()
        # because the group context is thread-local.
        self._collective = (world_size, rank, group_name)
        return True

    def run(self, fn_payload) -> bool:
        import cloudpickle

        from .session import bind_thread_session

        fn, config = cloudpickle.loads(fn_payload)

        def target():
            bind_thread_session(self.session)
            try:
                if self._collective is not None:
                    from .. import collective

                    world, rank, group = self._collective
                    collective.init_collective_group(world, rank, group_name=group)
                if config is not None:
                    fn(config)
                else:
                    fn()
            except BaseException as e:  # noqa: BLE001
                self.session.error = e
                self.session.error_tb = traceback.format_exc()
            finally:
                # Land any still-queued async checkpoint shards before the
                # executor can treat this worker as finished — "finished"
                # must imply "reported checkpoints are durable". close()
                # also stops the writer thread (local mode shares one
                # process across workers AND incarnations; a flush-only
                # teardown would leak one parked thread per restart).
                es = getattr(self.session, "elastic", None)
                if es is not None:
                    try:
                        es.close()
                    except Exception as ce:  # noqa: BLE001
                        # A shard that never landed is a worker failure —
                        # finishing "successfully" would let a later
                        # restore silently resume from an older step.
                        if self.session.error is None:
                            self.session.error = ce
                            self.session.error_tb = traceback.format_exc()
                self.session.finished.set()

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self, from_index=None):
        """Returns (results, finished, error_str). With `from_index` (int),
        a NON-destructive read of reports from that cursor — idempotent, so
        a response lost in flight (gang poll batch failing on a dead
        sibling) costs nothing: the caller re-polls from the same cursor.
        Without it, drain semantics (tune's tuner polls this way)."""
        # finished is read BEFORE the results snapshot: the loop thread
        # appends its last report strictly before finished.set(), so
        # finished=True here guarantees the snapshot below contains every
        # report. Snapshot-then-read would let a final report land in the
        # window and be dropped forever when the caller stops polling on
        # finished=True.
        finished = self.session.finished.is_set()
        if from_index is None:
            out = []
            while not self.session.results.empty():
                out.append(self.session.results.get())
            # Drain consumers never cursor-ack, so retire the drained
            # entries from the cursor history too — otherwise a long
            # drain-polled run (tune's tuner) retains every report and
            # in-memory checkpoint payload for the life of the worker.
            n = min(len(out), len(self.session.history))
            if n:
                del self.session.history[:n]
                self.session.history_base += n
        else:
            base = self.session.history_base
            out = list(self.session.history[max(from_index - base, 0):])
            # Implicit ack: a caller polling from N has durably consumed
            # everything below N — trim it, and discard the legacy queue
            # this consumer will never drain, so per-worker memory stays
            # bounded by one poll interval on long runs.
            if from_index > base:
                del self.session.history[: from_index - base]
                self.session.history_base = from_index
            while not self.session.results.empty():
                try:
                    self.session.results.get_nowait()
                except Exception:  # noqa: BLE001 — racing reporter, fine
                    break
        err = None
        if self.session.error is not None:
            err = f"{self.session.error!r}\n{getattr(self.session, 'error_tb', '')}"
        return out, finished, err

    def set_checkpoint(self, checkpoint):
        self.context.latest_checkpoint = checkpoint
        return True

    def execute(self, fn_payload):
        """Synchronously run a function on the worker (for utilities)."""
        import cloudpickle

        fn = cloudpickle.loads(fn_payload)
        return fn()


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        contexts: List[Dict[str, Any]],
        placement_strategy: str = "PACK",
    ):
        import cloudpickle

        self._cloudpickle = cloudpickle
        remote_cls = api.remote(TrainWorker)
        cpus = resources_per_worker.get("CPU", 1.0)
        tpus = resources_per_worker.get("TPU", 0.0)
        extra = {
            k: v for k, v in resources_per_worker.items() if k not in ("CPU", "TPU")
        }
        # Gang placement: one bundle per worker, worker i pinned to bundle i
        # (reference: `BackendExecutor` creating the Train placement group;
        # TPU slice gangs are STRICT_PACK per `accelerators/tpu.py:199-313`).
        self._pg = None
        strategy_kwargs: List[Dict[str, Any]] = [{} for _ in range(num_workers)]
        bundle = {k: v for k, v in {"CPU": cpus, "TPU": tpus, **extra}.items() if v}
        if bundle:
            from ..core.task_spec import PlacementGroupSchedulingStrategy
            from ..util.placement_group import placement_group

            try:
                pg = placement_group(
                    [dict(bundle) for _ in range(num_workers)],
                    strategy=placement_strategy,
                )
                if pg.wait(timeout_seconds=30):
                    self._pg = pg
                    strategy_kwargs = [
                        {
                            "scheduling_strategy": PlacementGroupSchedulingStrategy(
                                placement_group=pg,
                                placement_group_bundle_index=i,
                            )
                        }
                        for i in range(num_workers)
                    ]
                else:  # infeasible as a gang — fall back to free placement
                    from ..util.placement_group import remove_placement_group

                    remove_placement_group(pg)
            except Exception:  # noqa: BLE001 — backend without PG support
                self._pg = None
        self.workers = [
            remote_cls.options(
                num_cpus=cpus, num_tpus=tpus or None, resources=extra or {},
                **strategy_kwargs[i],
            ).remote(contexts[i])
            for i in range(num_workers)
        ]

    def __len__(self):
        return len(self.workers)

    def actor_ids(self) -> List[str]:
        """Hex actor ids of the gang members — the unit the supervisor
        watches for death events and chaos harnesses target for kills."""
        return [w._id.hex() for w in self.workers]

    def run_async(self, fn: Callable, config=None):
        payload = self._cloudpickle.dumps((fn, config))
        return api.get([w.run.remote(payload) for w in self.workers])

    def poll(self, cursors: Optional[List[int]] = None):
        if cursors is None:
            return api.get([w.poll.remote() for w in self.workers])
        return api.get(
            [w.poll.remote(c) for w, c in zip(self.workers, cursors)]
        )

    def execute_all(self, fn: Callable):
        payload = self._cloudpickle.dumps(fn)
        return api.get([w.execute.remote(payload) for w in self.workers])

    def execute_single(self, index: int, fn: Callable):
        payload = self._cloudpickle.dumps(fn)
        return api.get(self.workers[index].execute.remote(payload))

    def set_env_all(self, envs: List[Dict[str, str]]):
        return api.get(
            [w.set_env.remote(env) for w, env in zip(self.workers, envs)]
        )

    def setup_collective(self, group_name: str):
        refs = [
            w.setup_collective.remote(len(self.workers), i, group_name)
            for i, w in enumerate(self.workers)
        ]
        return api.get(refs, timeout=120)

    def set_checkpoint_all(self, checkpoint):
        return api.get([w.set_checkpoint.remote(checkpoint) for w in self.workers])

    def shutdown(self):
        for w in self.workers:
            try:
                api.kill(w)
            except Exception:  # noqa: BLE001
                pass
        if self._pg is not None:
            from ..util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001
                pass
            self._pg = None
