"""HuggingFace Transformers interop: GPT-2-family checkpoints ↔ ray_tpu GPT.

Reference analog: `python/ray/train/huggingface/` (TransformersTrainer et
al.) — the reference wraps HF's torch Trainer inside a DDP gang, so torch
runs the accelerator math. TPU redesign: convert the HF checkpoint ONCE
into this framework's jax param layout (`params_from_hf`), train with the
native pjit GPT train step (torch never touches the TPU), and export back
to an HF state dict (`params_to_hf_state_dict`) for the torch serving
ecosystem. Conversion is exact — `tests/test_hf_interop.py` gates logits
of the converted model against the torch forward.

Layout notes (HF GPT-2 `Conv1D` stores [in, out], which matches our
einsum-ready layouts directly):
    c_attn.weight [E, 3E]  -> w_qkv [E, 3, H, Dh]   (qkv blocks, head-major)
    c_proj.weight [E, E]   -> w_o   [H, Dh, E]
    mlp.c_fc / c_proj      -> w_in [E, F] / w_out [F, E]
HF's vocab (50257) is zero-padded up to our MXU-friendly multiple of 128
(50304); padded rows never receive gradient signal from real tokens and are
sliced off again on export.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..models.gpt import GPTConfig
from .checkpoint import Checkpoint
from .config import RunConfig, ScalingConfig
from .jax_trainer import JaxTrainer


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _np(t, dtype):
    return np.asarray(t.detach().cpu().numpy(), dtype)


def config_from_hf(hf_config, **overrides) -> GPTConfig:
    """GPT2Config -> GPTConfig (vocab padded to a multiple of 128)."""
    E, H = hf_config.n_embd, hf_config.n_head
    kw: Dict[str, Any] = dict(
        vocab_size=_round_up(hf_config.vocab_size, 128),
        n_layers=hf_config.n_layer,
        d_model=E,
        n_heads=H,
        d_head=E // H,
        d_mlp=(getattr(hf_config, "n_inner", None) or 4 * E),
        max_seq=hf_config.n_positions,
        norm="layernorm",
        activation="gelu",
        pos="learned",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def _strip_prefix(sd: Dict[str, Any]) -> Dict[str, Any]:
    return {
        (k[len("transformer."):] if k.startswith("transformer.") else k): v
        for k, v in sd.items()
    }


def params_from_hf(
    model, cfg: Optional[GPTConfig] = None, dtype=np.float32
) -> Tuple[Dict[str, np.ndarray], GPTConfig]:
    """GPT2LMHeadModel / GPT2Model / state_dict -> (params, cfg).

    Params come back as numpy (master-precision f32 by default) — feed them
    to `jax.device_put` with your shardings; `models.gpt.forward` casts to
    cfg.dtype layer by layer.
    """
    if hasattr(model, "state_dict"):
        sd = model.state_dict()
        if cfg is None:
            cfg = config_from_hf(model.config)
    else:
        sd = model
        if cfg is None:
            raise ValueError("pass cfg= when converting a raw state_dict")
    sd = _strip_prefix(sd)
    L, E, H, Dh = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.d_head

    def one(key):
        return _np(sd[key], dtype)

    def stack(key):
        return np.stack([_np(sd[f"h.{i}.{key}"], dtype) for i in range(L)])

    wte = one("wte.weight")
    tok = np.zeros((cfg.vocab_size, E), dtype)
    tok[: wte.shape[0]] = wte
    params: Dict[str, np.ndarray] = {
        "tok_embed": tok,
        "pos_embed": one("wpe.weight"),
        "ln_f_w": one("ln_f.weight"),
        "ln_f_b": one("ln_f.bias"),
        "w_qkv": stack("attn.c_attn.weight").reshape(L, E, 3, H, Dh),
        "b_qkv": stack("attn.c_attn.bias").reshape(L, 3, H, Dh),
        "w_o": stack("attn.c_proj.weight").reshape(L, H, Dh, E),
        "b_o": stack("attn.c_proj.bias"),
        "ln1_w": stack("ln_1.weight"),
        "ln1_b": stack("ln_1.bias"),
        "ln2_w": stack("ln_2.weight"),
        "ln2_b": stack("ln_2.bias"),
        "w_in": stack("mlp.c_fc.weight"),
        "b_in": stack("mlp.c_fc.bias"),
        "w_out": stack("mlp.c_proj.weight"),
        "b_out": stack("mlp.c_proj.bias"),
    }
    if not cfg.tie_embeddings:
        lm = one("lm_head.weight")  # [V, E]
        head = np.zeros((E, cfg.vocab_size), dtype)
        head[:, : lm.shape[0]] = lm.T
        params["lm_head"] = head
    return params, cfg


def params_to_hf_state_dict(
    params: Dict[str, Any], cfg: GPTConfig, hf_vocab_size: Optional[int] = None
) -> Dict[str, Any]:
    """Inverse of `params_from_hf` (torch tensors, vocab padding sliced
    off) — load into a GPT2LMHeadModel with `load_state_dict(strict=False)`
    (HF keeps non-parameter `attn.bias` mask buffers we don't carry)."""
    import torch

    L, E, H, Dh = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.d_head
    V = hf_vocab_size or cfg.vocab_size

    def t(a):
        return torch.from_numpy(np.ascontiguousarray(np.asarray(a, np.float32)))

    p = {k: np.asarray(v) for k, v in params.items()}
    sd = {
        "transformer.wte.weight": t(p["tok_embed"][:V]),
        "transformer.wpe.weight": t(p["pos_embed"]),
        "transformer.ln_f.weight": t(p["ln_f_w"]),
        "transformer.ln_f.bias": t(p["ln_f_b"]),
        "lm_head.weight": t(
            p["tok_embed"][:V]
            if cfg.tie_embeddings
            else p["lm_head"].T[:V]
        ),
    }
    for i in range(L):
        h = f"transformer.h.{i}"
        sd[f"{h}.attn.c_attn.weight"] = t(p["w_qkv"][i].reshape(E, 3 * H * Dh))
        sd[f"{h}.attn.c_attn.bias"] = t(p["b_qkv"][i].reshape(3 * H * Dh))
        sd[f"{h}.attn.c_proj.weight"] = t(p["w_o"][i].reshape(H * Dh, E))
        sd[f"{h}.attn.c_proj.bias"] = t(p["b_o"][i])
        sd[f"{h}.ln_1.weight"] = t(p["ln1_w"][i])
        sd[f"{h}.ln_1.bias"] = t(p["ln1_b"][i])
        sd[f"{h}.ln_2.weight"] = t(p["ln2_w"][i])
        sd[f"{h}.ln_2.bias"] = t(p["ln2_b"][i])
        sd[f"{h}.mlp.c_fc.weight"] = t(p["w_in"][i])
        sd[f"{h}.mlp.c_fc.bias"] = t(p["b_in"][i])
        sd[f"{h}.mlp.c_proj.weight"] = t(p["w_out"][i])
        sd[f"{h}.mlp.c_proj.bias"] = t(p["b_out"][i])
    return sd


# ----------------------------------------------------------------- trainer
def _default_train_loop(config: Dict[str, Any]):
    """Per-worker finetune loop: converted HF params + the native GPT train
    step under jit, batches from the Ray Data shard."""
    import jax
    import optax

    from .. import train
    from ..models import gpt

    cfg: GPTConfig = config["gpt_config"]
    params = {k: jax.device_put(v) for k, v in config["init_params"].items()}
    opt = optax.adamw(
        config.get("lr", 5e-5), weight_decay=config.get("weight_decay", 0.01)
    )
    state = (params, opt.init(params))
    step = jax.jit(gpt.make_train_step(cfg, opt), donate_argnums=(0,))

    shard = train.get_dataset_shard("train")
    steps = int(config.get("steps", 100))
    bsz = int(config.get("batch_size", 8))
    done = 0
    last = float("nan")
    while done < steps:
        got_any = False
        for batch in shard.iter_jax_batches(batch_size=bsz, drop_last=True):
            got_any = True
            if done >= steps:
                break
            state, metrics = step(state, {"tokens": batch["tokens"]})
            last = float(metrics["loss"])
            done += 1
            if done % max(1, steps // 5) == 0:
                train.report({"loss": last, "step": done})
        if not got_any:
            raise ValueError(
                f"train dataset shard yields no batches at batch_size={bsz} "
                "with drop_last=True — fewer rows than one batch?"
            )
    final = {k: np.asarray(v) for k, v in state[0].items()}
    train.report(
        {"loss": last, "step": done, "done": True},
        checkpoint=Checkpoint.from_dict(
            {"params": final, "hf_state_dict_ready": True}
        ),
    )


class TransformersTrainer(JaxTrainer):
    """Finetune an HF GPT-2-family model with the native TPU train step.

    Reference analog: `python/ray/train/huggingface/transformers/` — same
    job (HF checkpoint in, finetuned checkpoint out, Ray Data in the
    middle), different engine (pjit GPT instead of a wrapped torch
    Trainer). The checkpoint's `params` convert back to an HF state dict
    via `params_to_hf_state_dict`.

        trainer = TransformersTrainer(
            model=GPT2LMHeadModel(cfg),        # or (params, gpt_config)
            datasets={"train": ds},            # {"tokens": [S+1] int32} rows
            train_loop_config={"steps": 50, "batch_size": 8, "lr": 5e-5},
            scaling_config=ScalingConfig(num_workers=1),
        )
        result = trainer.fit()
    """

    def __init__(
        self,
        *,
        model,
        datasets,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        gpt_config: Optional[GPTConfig] = None,
        train_loop_per_worker=None,
    ):
        if isinstance(model, tuple):
            init_params, cfg = model
            if gpt_config is not None:
                cfg = gpt_config
        else:
            init_params, cfg = params_from_hf(model, gpt_config)
        loop_cfg = dict(train_loop_config or {})
        loop_cfg["gpt_config"] = cfg
        loop_cfg["init_params"] = init_params
        super().__init__(
            train_loop_per_worker or _default_train_loop,
            train_loop_config=loop_cfg,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
