"""TorchTrainer — CPU-torch data-parallel training on the cluster.

Reference analog: `python/ray/train/torch/` (`TorchTrainer`,
`_TorchBackend.on_start` → `dist.init_process_group` in
`torch/config.py:106,148`, and `prepare_model`/`prepare_data_loader` in
`train_loop_utils.py:74,369`).

Role here: parity for torch-based workloads on CPU fleets (this framework's
accelerator path is JAX/TPU — see `jax_trainer.py`; torch on TPU is a
non-goal). The gang wires a gloo process group exactly like the reference's
CPU path; `prepare_model` wraps DDP, `prepare_data_loader` injects a
DistributedSampler.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from .config import RunConfig, ScalingConfig
from .data_parallel_trainer import CollectiveBackend, DataParallelTrainer


class TorchBackend(CollectiveBackend):
    """Arranges MASTER_ADDR/PORT/RANK/WORLD_SIZE across the gang; workers
    call `ray_tpu.train.torch.prepare()` (or init_process_group directly)."""

    def on_start(self, worker_group, scaling):
        super().on_start(worker_group, scaling)
        n = len(worker_group)
        from .jax_trainer import _coordinator_binding

        ip, port = worker_group.execute_single(0, _coordinator_binding)
        envs = [
            {
                "MASTER_ADDR": ip,
                "MASTER_PORT": str(port),
                "RANK": str(i),
                "WORLD_SIZE": str(n),
                "LOCAL_RANK": "0",
            }
            for i in range(n)
        ]
        worker_group.set_env_all(envs)


class TorchTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict] = None,
        resume_from_checkpoint=None,
    ):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            backend=TorchBackend(),
            resume_from_checkpoint=resume_from_checkpoint,
        )


# ------------------------------------------------------- in-loop utilities
def prepare():
    """Initialize the gloo process group from the gang env (call once at the
    top of train_loop_per_worker). Reference analog: automatic
    `dist.init_process_group` in `_TorchBackend.on_start`."""
    import os

    import torch.distributed as dist

    if dist.is_initialized():
        return
    world = int(os.environ.get("WORLD_SIZE", "1"))
    if world <= 1:
        return
    dist.init_process_group(
        backend="gloo",
        rank=int(os.environ["RANK"]),
        world_size=world,
    )


def prepare_model(model):
    """Wrap in DDP when distributed (reference: `prepare_model`,
    `train_loop_utils.py:74` — CPU/gloo path, no device move)."""
    import torch.distributed as dist

    prepare()
    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return model
    from torch.nn.parallel import DistributedDataParallel

    return DistributedDataParallel(model)


def prepare_data_loader(data_loader):
    """Re-build the DataLoader with a DistributedSampler so each worker sees
    its shard (reference: `prepare_data_loader`, `train_loop_utils.py:369`)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    prepare()
    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return data_loader
    sampler = DistributedSampler(
        data_loader.dataset,
        num_replicas=dist.get_world_size(),
        rank=dist.get_rank(),
        shuffle=True,
    )
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )


def get_device():
    """Reference-API parity; the torch path here is CPU-only."""
    import torch

    return torch.device("cpu")
