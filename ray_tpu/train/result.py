"""Result object returned by Trainer.fit (reference: `python/ray/air/result.py`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    path: str = ""

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []
