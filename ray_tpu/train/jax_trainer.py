"""JaxTrainer — the TPU-native trainer.

This is the component BASELINE.json's north star names: the reference's
`TorchTrainer` + NCCL process groups (`train/torch/config.py:106,148`)
replaced by a JAX/pjit backend. Key inversion: the reference runs one worker
per GPU and wires a NCCL communicator between them; here one worker runs per
HOST, owns all local chips, and the gang assembles ONE global mesh —
in-step communication is compiled by XLA onto ICI, with `jax.distributed`
over DCN for multi-host.

Inside `train_loop_per_worker`:
    ctx  = ray_tpu.train.get_context()
    mesh = ray_tpu.train.jax_utils.get_mesh()        # gang-wide Mesh
    step = jax.jit(train_step, in_shardings=..., ...)  # XLA does the rest
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from .backend_executor import Backend
from .config import RunConfig, ScalingConfig
from .data_parallel_trainer import CollectiveBackend, DataParallelTrainer


class JaxBackend(CollectiveBackend):
    """Arranges `jax.distributed` env across the gang.

    Worker 0 becomes the coordinator; every worker gets
    JAX_COORDINATOR_ADDRESS / process id env so user code (or
    `jax_utils.maybe_init_distributed`) can call
    `jax.distributed.initialize` and see the union of all hosts' chips in
    `jax.devices()`.
    """

    def on_start(self, worker_group, scaling):
        super().on_start(worker_group, scaling)
        n = len(worker_group)
        if n <= 1:
            return
        # Worker 0 hosts the coordinator: resolve ITS address (gang workers
        # may sit on different nodes via the placement group), then pick a
        # port on that host.
        coord_ip, port = worker_group.execute_single(0, _coordinator_binding)
        coord = f"{coord_ip}:{port}"
        envs = [
            {
                "RAY_TPU_JAX_COORDINATOR": coord,
                "RAY_TPU_JAX_NUM_PROCESSES": str(n),
                "RAY_TPU_JAX_PROCESS_ID": str(i),
            }
            for i in range(n)
        ]
        worker_group.set_env_all(envs)


def _coordinator_binding():
    """Runs ON worker 0: its routable IP + a free port on that host."""
    import socket

    ip = "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))  # no packets sent; just picks a route
        ip = s.getsockname()[0]
    except OSError:
        pass
    finally:
        s.close()
    ps = socket.socket()
    ps.bind((ip if ip != "127.0.0.1" else "127.0.0.1", 0))
    port = ps.getsockname()[1]
    ps.close()
    return ip, port


class JaxTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict] = None,
        resume_from_checkpoint=None,
    ):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend=JaxBackend(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )


# ------------------------------------------------------------------- utils
class jax_utils:
    """Worker-side helpers (reference analog: `train/torch/train_loop_utils.py`
    `prepare_model`/`get_device` — except there is nothing to wrap: sharding
    specs replace DDP)."""

    @staticmethod
    def maybe_init_distributed():
        """Join the gang-wide jax runtime if this gang spans hosts."""
        import jax

        # Session env is authoritative (os.environ is shared between workers
        # in local mode and would hand every worker the last rank's id).
        from .session import get_context

        env = dict(os.environ)
        env.update(get_context().env_vars)
        coord = env.get("RAY_TPU_JAX_COORDINATOR")
        if not coord:
            return False
        num = int(env["RAY_TPU_JAX_NUM_PROCESSES"])
        pid = int(env["RAY_TPU_JAX_PROCESS_ID"])
        # Idempotent ONLY for the same gang: a worker process may run several
        # gang loops (actor reuse), but jax.distributed initializes once per
        # process — joining a *different* coordinator is impossible, so fail
        # loudly rather than let the new gang hang in rendezvous.
        try:
            from jax._src import distributed as _dist

            gs = _dist.global_state
        except Exception:  # noqa: BLE001 — private API moved on a jax
            # upgrade; fall through to initialize (pre-guard behavior). A
            # genuine double-init then raises from jax itself.
            gs = None
        if gs is not None and getattr(gs, "client", None) is not None:
            have = (gs.coordinator_address, gs.num_processes, gs.process_id)
            if have == (coord, num, pid):
                return True
            raise RuntimeError(
                f"jax.distributed already initialized for a different gang "
                f"(have coordinator/num/pid {have}, want {(coord, num, pid)}); "
                f"this process cannot re-join — restart the gang with fresh "
                f"workers (WorkerGroup.shutdown kills them)"
            )
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=num,
            process_id=pid,
        )
        return True

    @staticmethod
    def get_mesh(**axis_sizes):
        """Build the gang-wide mesh (default: pure dp over all chips)."""
        import jax

        from ..parallel import make_mesh

        if not axis_sizes:
            axis_sizes = {"dp": -1}
        return make_mesh(jax.devices(), **axis_sizes)
