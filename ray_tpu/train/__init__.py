"""ray_tpu.train — distributed training library.

API parity target: `ray.train` (`python/ray/train/__init__.py` — SURVEY.md
Appendix A): report / get_context / get_checkpoint / get_dataset_shard,
Checkpoint, RunConfig / ScalingConfig / CheckpointConfig / FailureConfig,
Result, and trainers.

TPU-first redesign: where the reference's `TorchTrainer` wires
`dist.init_process_group(nccl)` into each worker (`torch/config.py:106`),
`JaxTrainer` gangs one worker per HOST and builds a global `jax.sharding`
Mesh across them (`jax.distributed` for multi-host); within a host, data
parallelism is pjit over local chips — workers never see NCCL or per-chip
process groups.
"""

from .config import (
    CheckpointConfig,
    DataConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from .checkpoint import Checkpoint
from .session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_elastic_session,
    get_streaming_ingest,
    report,
)
from .result import Result
from .base_trainer import BaseTrainer
from .data_parallel_trainer import DataParallelTrainer
from .gbdt_trainer import GBDTTrainer, XGBoostTrainer
from .jax_trainer import JaxTrainer
from . import elastic  # noqa: F401 — fault-tolerant gang training (ISSUE 4)
from . import huggingface  # noqa: F401 — HF checkpoint interop (GPT-2 family)
from . import torch_trainer as torch  # ray_tpu.train.torch.prepare_model(...)
from .torch_trainer import TorchTrainer

__all__ = [
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "get_elastic_session",
    "get_streaming_ingest",
    "elastic",
    "Checkpoint",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "CheckpointConfig",
    "FailureConfig",
    "DataConfig",
    "BaseTrainer",
    "DataParallelTrainer",
    "GBDTTrainer",
    "XGBoostTrainer",
    "JaxTrainer",
    "TorchTrainer",
    "torch",
]
