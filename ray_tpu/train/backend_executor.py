"""BackendExecutor — orchestrates a training worker gang.

Reference analog: `python/ray/train/_internal/backend_executor.py:65`
(`start` `:124`, `start_training` `:438`): create WorkerGroup, let the
backend configure the gang (the reference runs `dist.init_process_group`;
our JaxBackend assembles mesh env instead), push the user loop, poll
results, manage checkpoints, restart the gang on failure (gang semantics:
one worker dies → the whole group restarts — SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from .checkpoint import CheckpointManager
from .config import FailureConfig, RunConfig, ScalingConfig
from .result import Result
from .worker_group import WorkerGroup


class Backend:
    """Per-framework gang setup hook (reference: `BackendConfig`/`Backend`)."""

    def on_start(self, worker_group: WorkerGroup, scaling: ScalingConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class BackendExecutor:
    def __init__(
        self,
        backend: Backend,
        scaling: ScalingConfig,
        run_config: RunConfig,
        experiment_name: str = "train",
    ):
        self.backend = backend
        self.scaling = scaling
        self.run_config = run_config
        self.experiment_name = experiment_name
        self.worker_group: Optional[WorkerGroup] = None
        # Shards re-attached on every (re)start so gang restarts keep data.
        self.dataset_shards: Optional[Dict[str, list]] = None
        storage = run_config.resolve_storage()
        ckpt_cfg = run_config.checkpoint_config
        self.checkpoint_manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        self._latest_checkpoint = None

    def start(self):
        n = self.scaling.num_workers
        contexts = [
            dict(
                world_rank=i,
                world_size=n,
                local_rank=i,  # single-machine runtime; multi-node refines this
                node_rank=0,
                experiment_name=self.experiment_name,
                storage_path=self.run_config.resolve_storage(),
            )
            for i in range(n)
        ]
        self.worker_group = WorkerGroup(
            n,
            self.scaling.worker_resources(),
            contexts,
            self.scaling.placement_strategy,
        )
        # Rank env vars (reference: backend_executor.py:358).
        envs = [
            {
                "RAY_TPU_TRAIN_WORLD_RANK": str(i),
                "RAY_TPU_TRAIN_WORLD_SIZE": str(n),
            }
            for i in range(n)
        ]
        self.worker_group.set_env_all(envs)
        if self._latest_checkpoint is not None:
            self.worker_group.set_checkpoint_all(self._latest_checkpoint)
        if self.dataset_shards:
            self._attach_shards()
        self.backend.on_start(self.worker_group, self.scaling)

    def set_datasets(self, datasets: Dict[str, Any]):
        n = self.scaling.num_workers
        self.dataset_shards = {}
        for name, ds in datasets.items():
            shards = (
                ds.streaming_split(n) if hasattr(ds, "streaming_split") else [ds] * n
            )
            self.dataset_shards[name] = shards

    def _attach_shards(self):
        import cloudpickle

        from ..core import api

        for name, shards in self.dataset_shards.items():
            for worker, shard in zip(self.worker_group.workers, shards):
                api.get(worker.execute.remote(cloudpickle.dumps(_shard_setter(name, shard))))

    def run(
        self,
        train_fn: Callable,
        config: Optional[dict],
        datasets: Optional[dict] = None,
    ) -> Result:
        failure_cfg = self.run_config.failure_config
        attempts = 0
        while True:
            try:
                return self._run_once(train_fn, config)
            except _WorkerGroupError as e:
                attempts += 1
                if failure_cfg.max_failures >= 0 and attempts > failure_cfg.max_failures:
                    return Result(
                        metrics={},
                        checkpoint=self.checkpoint_manager.latest(),
                        error=str(e),
                        path=self.run_config.resolve_storage(),
                    )
                # Gang restart: tear down every worker, restore from the
                # latest checkpoint (or the original resume checkpoint when
                # the failure predates any new one), run the loop again.
                if self.worker_group is not None:
                    self.worker_group.shutdown()
                self._latest_checkpoint = (
                    self.checkpoint_manager.latest() or self._latest_checkpoint
                )
                self.start()

    def _run_once(self, train_fn, config) -> Result:
        if self.worker_group is None:
            self.start()
        wg = self.worker_group
        wg.run_async(train_fn, config)

        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        while True:
            polls = wg.poll()
            # Align result batches across workers; rank-0 metrics win
            # (reference semantics: all workers report, rank 0 is canonical).
            for batch_idx in range(max(len(p[0]) for p in polls) if polls else 0):
                rank0 = polls[0][0]
                if batch_idx < len(rank0):
                    entry = rank0[batch_idx]
                    metrics = entry["metrics"]
                    ckpt = entry.get("checkpoint")
                    if ckpt is None:
                        for p in polls[1:]:
                            if batch_idx < len(p[0]) and p[0][batch_idx].get("checkpoint"):
                                ckpt = p[0][batch_idx]["checkpoint"]
                                break
                    if ckpt is not None:
                        self.checkpoint_manager.register(ckpt, metrics)
                    history.append(metrics)
                    last_metrics = metrics
            errors = [p[2] for p in polls if p[2]]
            if errors:
                raise _WorkerGroupError("; ".join(errors))
            if all(p[1] for p in polls):
                break
            time.sleep(0.05)

        return Result(
            metrics=last_metrics,
            checkpoint=self.checkpoint_manager.latest(),
            metrics_history=history,
            path=self.run_config.resolve_storage(),
        )

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None


class _WorkerGroupError(RuntimeError):
    pass


def _shard_setter(name, shard):
    def setter():
        from .session import get_session

        s = get_session()
        if s is not None:
            s.context.dataset_shards[name] = shard
        return True

    return setter
