"""BackendExecutor — orchestrates a training worker gang.

Reference analog: `python/ray/train/_internal/backend_executor.py:65`
(`start` `:124`, `start_training` `:438`): create WorkerGroup, let the
backend configure the gang (the reference runs `dist.init_process_group`;
our JaxBackend assembles mesh env instead), push the user loop, poll
results, manage checkpoints.

Failure policy (train/elastic, ISSUE 4): gang semantics — one worker dies →
the WHOLE group aborts and restarts (SURVEY.md §7 hard parts). `run()`
loops on GangSupervisor verdicts: every failure (a worker-reported error, a
failed actor call, or a controller death event the supervisor saw first)
becomes a `_WorkerGroupError`; the supervisor aborts the mesh within its
deadline (collectives interrupted, no wedged barrier), decides
restart/shrink/stop against its budget + backoff, and the gang re-forms —
restoring from the latest committed checkpoint with the elasticity band
applied to the new world size.

The MPMD pipeline trainer (`ray_tpu.train.mpmd.trainer`) runs the same
supervisor-verdict loop for its S x dp stage gang — watch -> abort (every
stage's collective group) -> budget/backoff -> reshape (dp re-picked from
feasible capacity) -> restore from the pipeline's common committed step —
with per-stage checkpoint directories instead of this executor's single
gang root.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import replace
from typing import Any, Callable, Dict, List, Optional

from .checkpoint import CheckpointManager
from .config import FailureConfig, RunConfig, ScalingConfig
from .result import Result
from .worker_group import WorkerGroup


class Backend:
    """Per-framework gang setup hook (reference: `BackendConfig`/`Backend`)."""

    def on_start(self, worker_group: WorkerGroup, scaling: ScalingConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class BackendExecutor:
    def __init__(
        self,
        backend: Backend,
        scaling: ScalingConfig,
        run_config: RunConfig,
        experiment_name: str = "train",
    ):
        self.backend = backend
        self.scaling = scaling
        self.run_config = run_config
        self.experiment_name = experiment_name
        self.worker_group: Optional[WorkerGroup] = None
        # Raw datasets + shards; shards are re-split on every (re)start so
        # gang restarts — including elastic shrinks — keep data coverage.
        self._datasets: Optional[Dict[str, Any]] = None
        self.dataset_shards: Optional[Dict[str, list]] = None
        # Gang incarnation token: one per start(); all ranks of one
        # incarnation share it (elastic checkpoint dirs are keyed by it so
        # two incarnations can never mix shards into one checkpoint).
        self.elastic_gen: str = "0"
        # Run-identity namespace for the elastic checkpoint root: stable
        # across this run's gang restarts, but distinct between runs. A
        # NAMED run keeps its name (elastic resume across driver restarts
        # is then opt-in and explicit, like resume_latest); an unnamed run
        # gets a fresh token so two unrelated runs sharing the default
        # storage path can never silently restore each other's weights.
        self.elastic_run_ns: str = (
            run_config.name or f"anon-{uuid.uuid4().hex[:8]}"
        )
        storage = run_config.resolve_storage()
        ckpt_cfg = run_config.checkpoint_config
        self.checkpoint_manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        self._latest_checkpoint = None
        self._supervisor = None
        # Absolute poll-entry indices whose checkpoint was registered, per
        # incarnation (reset with the cursors in _run_once).
        self._ckpt_reg_idxs: set = set()

    def start(self):
        n = self.scaling.num_workers
        self.elastic_gen = uuid.uuid4().hex[:8]
        contexts = [
            dict(
                world_rank=i,
                world_size=n,
                local_rank=i,  # single-machine runtime; multi-node refines this
                node_rank=0,
                experiment_name=self.experiment_name,
                storage_path=self.run_config.resolve_storage(),
            )
            for i in range(n)
        ]
        self.worker_group = WorkerGroup(
            n,
            self.scaling.worker_resources(),
            contexts,
            self.scaling.placement_strategy,
        )
        # Rank env vars (reference: backend_executor.py:358).
        envs = [
            {
                "RAY_TPU_TRAIN_WORLD_RANK": str(i),
                "RAY_TPU_TRAIN_WORLD_SIZE": str(n),
                "RAY_TPU_TRAIN_ELASTIC_GEN": self.elastic_gen,
                "RAY_TPU_TRAIN_ELASTIC_RUN": self.elastic_run_ns,
            }
            for i in range(n)
        ]
        self.worker_group.set_env_all(envs)
        if self._latest_checkpoint is not None:
            self.worker_group.set_checkpoint_all(self._latest_checkpoint)
        if self._datasets:
            self._reshard_datasets(n)
            self._attach_shards()
        self.backend.on_start(self.worker_group, self.scaling)

    def set_datasets(self, datasets: Dict[str, Any]):
        # Split happens in start() (and on every restart) — splitting here
        # too would just be discarded by the start-time reshard.
        self._datasets = dict(datasets)

    def _reshard_datasets(self, n: int):
        self.dataset_shards = {}
        for name, ds in (self._datasets or {}).items():
            shards = (
                ds.streaming_split(n) if hasattr(ds, "streaming_split") else [ds] * n
            )
            self.dataset_shards[name] = shards

    def _attach_shards(self):
        import cloudpickle

        from ..core import api

        for name, shards in self.dataset_shards.items():
            for worker, shard in zip(self.worker_group.workers, shards):
                api.get(worker.execute.remote(cloudpickle.dumps(_shard_setter(name, shard))))

    def run(
        self,
        train_fn: Callable,
        config: Optional[dict],
        datasets: Optional[dict] = None,
    ) -> Result:
        from .elastic import GangSupervisor

        supervisor = GangSupervisor(
            self.scaling,
            self.run_config.failure_config,
            experiment_name=self.experiment_name,
        )
        self._supervisor = supervisor
        collective_group = getattr(self.backend, "group_name", None)
        # Metrics history survives gang restarts: the steps a dead
        # incarnation reported are part of the run's trajectory.
        history: List[Dict[str, Any]] = []
        # Set at failure time; recovery (death -> re-formed gang) is
        # recorded once the NEXT incarnation has started successfully.
        recovery_t0: Optional[float] = None
        while True:
            try:
                if self.worker_group is None:
                    self._start_guarded()
                supervisor.watch(
                    self.worker_group, collective_group=collective_group
                )
                if recovery_t0 is not None:
                    supervisor.record_recovery(time.monotonic() - recovery_t0)
                    recovery_t0 = None
                result = self._run_once(train_fn, config, supervisor, history)
                supervisor.stop_watch()
                return result
            except _WorkerGroupError as e:
                if recovery_t0 is None:
                    recovery_t0 = time.monotonic()
                # Abort the ENTIRE mesh first (interrupt collectives, kill
                # survivors) — a member blocked on a dead peer must never
                # wedge until the round timeout.
                supervisor.abort_mesh(self.worker_group)
                self.worker_group = None
                decision = supervisor.on_failure(str(e))
                if decision.stop:
                    if (
                        e.during_start
                        and not history
                        and self.checkpoint_manager.latest() is None
                        and e.__cause__ is not None
                    ):
                        raise e.__cause__
                    logging.getLogger(__name__).error(
                        "gang failed permanently after %d attempt(s): %s",
                        supervisor.attempts, e,
                    )
                    return Result(
                        metrics=dict(history[-1]) if history else {},
                        checkpoint=self.checkpoint_manager.latest(),
                        error=str(e),
                        metrics_history=history,
                        path=self.run_config.resolve_storage(),
                    )
                # Gang restart: restore from the latest checkpoint (or the
                # original resume checkpoint when the failure predates any
                # new one), optionally shrunk within the elasticity band,
                # after the decided backoff. The start itself happens at
                # the loop top so a member dying MID-START consumes budget
                # like any other gang failure instead of escaping run().
                self._latest_checkpoint = (
                    self.checkpoint_manager.latest() or self._latest_checkpoint
                )
                # Every restart is logged: with max_failures=-1 a
                # deterministic failure retries forever, and a silent loop
                # would be indistinguishable from a hung run.
                logging.getLogger(__name__).warning(
                    "gang failure (%s) — restart attempt %d/%s after %.1fs",
                    e, supervisor.attempts,
                    "inf" if self.run_config.failure_config.max_failures < 0
                    else self.run_config.failure_config.max_failures,
                    decision.backoff_s,
                )
                if decision.backoff_s > 0:
                    time.sleep(decision.backoff_s)
                # World size is planned AFTER the backoff: the dead gang's
                # resources need the teardown to drain before a feasibility
                # reading means anything.
                world = supervisor.plan_world_size()
                if world and world != self.scaling.num_workers:
                    self.scaling = replace(self.scaling, num_workers=world)
                    supervisor.scaling = self.scaling

    def _start_guarded(self):
        """start() with gang-failure semantics: a member dying mid-start
        (env push, checkpoint broadcast, backend hook) tears down the
        partial group and surfaces as _WorkerGroupError so the elastic
        policy loop owns it."""
        try:
            self.start()
        except Exception as e:  # noqa: BLE001
            if self.worker_group is not None:
                try:
                    self.worker_group.shutdown()
                except Exception:  # noqa: BLE001
                    pass
                self.worker_group = None
            err = _WorkerGroupError(f"gang start failed: {e!r}")
            err.during_start = True
            raise err from e

    def _run_once(self, train_fn, config, supervisor=None, history=None) -> Result:
        if self.worker_group is None:
            self._start_guarded()
        wg = self.worker_group
        try:
            wg.run_async(train_fn, config)
        except Exception as e:  # noqa: BLE001 — a member died before launch
            raise _WorkerGroupError(f"gang launch failed: {e!r}") from e

        history = history if history is not None else []
        # Seed from the accumulated history: a restarted gang that resumes
        # exactly past the final step reports nothing, and Result.metrics
        # must still reflect the run's last reported step.
        last_metrics: Dict[str, Any] = dict(history[-1]) if history else {}
        # Cursor-based polls: reads are idempotent, so a poll RESPONSE lost
        # in flight (the batched get raising because a sibling died mid-
        # round) loses nothing — the salvage pass re-reads the survivors
        # from the last acknowledged cursor before the gang is aborted.
        cursors = [0] * len(wg)
        self._ckpt_reg_idxs = set()  # per-incarnation, like the cursors
        while True:
            # The supervisor usually sees a controller death event before a
            # poll call fails — surface it as the same gang failure.
            if supervisor is not None:
                reason = supervisor.failure()
                if reason:
                    self._salvage_polls(wg, cursors, history)
                    raise _WorkerGroupError(f"gang member died ({reason})")
            try:
                polls = wg.poll(cursors)
            except Exception as e:  # noqa: BLE001 — actor call failed (death)
                self._salvage_polls(wg, cursors, history)
                raise _WorkerGroupError(f"gang poll failed: {e!r}") from e
            # Align result batches across workers; rank-0 metrics win
            # (reference semantics: all workers report, rank 0 is canonical).
            try:
                consumed = self._consume_batches(
                    [p[0] for p in polls], history, offsets=cursors
                )
            except Exception as e:  # noqa: BLE001 — driver-side ckpt I/O
                # A checkpoint-registration failure (disk full, unwritable
                # storage) must flow through the SAME abort path as a gang
                # death: escaping run() raw would skip abort_mesh and leave
                # the (healthy, still-running) members wedged in their next
                # collective round. No salvage here — re-reading the same
                # window would just re-raise, and duplicate the entries
                # already appended to history.
                raise _WorkerGroupError(
                    f"checkpoint registration failed: {e!r}"
                ) from e
            if consumed is not None:
                last_metrics = consumed
            for i, p in enumerate(polls):
                cursors[i] += len(p[0])
            errors = [p[2] for p in polls if p[2]]
            if errors:
                raise _WorkerGroupError("; ".join(errors))
            if all(p[1] for p in polls):
                break
            time.sleep(0.05)

        return Result(
            metrics=last_metrics,
            checkpoint=self.checkpoint_manager.latest(),
            metrics_history=history,
            path=self.run_config.resolve_storage(),
        )

    def _consume_batches(self, batches, history, offsets=None):
        """Rank-0-canonical consumption of one poll window, aligned by
        ABSOLUTE entry index (`offsets[i]` = worker i's cursor at poll
        time): every member reports once per step from the same resumed
        step, so offset+position identifies the step even when the members
        drain unevenly across windows — positional pairing would drift by
        a constant once cursors diverge. This is the ONE place the policy
        lives; steady-state and salvage must agree. Rank 0's metrics drive
        history; a checkpoint comes from rank 0's entry or, at the same
        absolute index, from the first sibling carrying one — including
        indices rank 0 hasn't reached, because the caller acks (and trims)
        every worker's entries afterwards, so a checkpoint skipped here
        would be dropped forever. `_ckpt_reg_idxs` (reset per incarnation
        with the cursors) stops rank 0's later copy of an already-
        registered sibling checkpoint from landing twice. `batches[i]` is
        worker i's report list (None for an unreachable member). Returns
        the last rank-0 metrics consumed, or None."""
        offs = offsets or [0] * len(batches)
        rank0 = batches[0] or []
        last = None
        lo = min((offs[i] for i, b in enumerate(batches) if b), default=0)
        hi = max((offs[i] + len(b) for i, b in enumerate(batches) if b),
                 default=0)
        for idx in range(lo, hi):
            metrics = ckpt = None
            j0 = idx - offs[0]
            in_rank0 = 0 <= j0 < len(rank0)
            if in_rank0:
                entry = rank0[j0]
                metrics = entry["metrics"]
                ckpt = entry.get("checkpoint")
            if ckpt is None:
                for i, b in enumerate(batches[1:], start=1):
                    j = idx - offs[i]
                    if b and 0 <= j < len(b) and b[j].get("checkpoint"):
                        ckpt = b[j]["checkpoint"]
                        if metrics is None:
                            metrics = b[j]["metrics"]
                        break
            if ckpt is not None and idx not in self._ckpt_reg_idxs:
                self.checkpoint_manager.register(ckpt, metrics or {})
                self._ckpt_reg_idxs.add(idx)
            if in_rank0:
                history.append(metrics)
                last = metrics
        return last

    def _salvage_polls(self, wg, cursors, history):
        """Final drain of SURVIVING members' unconsumed reports before the
        mesh is aborted: rank 0 is the canonical metrics source, and the
        steps it reported between the last good poll and the sibling's
        death would otherwise vanish with the failed poll response —
        leaving a hole in the step trajectory that the post-restore re-run
        (which resumes from the last committed checkpoint, possibly past
        those steps) never fills.

        When rank 0 ITSELF is the casualty, its unpolled reports died with
        its process — so here (and only here: no further poll will ever
        deliver them) the hole is filled from the lowest surviving rank,
        aligned by absolute entry index (every member reports once per step
        from the same resumed step, so cursor+offset identifies the step
        regardless of how unevenly the main loop drained the members).
        Best-effort by nature: a step whose entry was already acked on
        every survivor before rank 0's copy arrived stays lost."""
        from ..core import api

        # Every survivor is drained, not just rank 0: the main loop's
        # checkpoint fallback scans polls[1:] when rank 0's entry carries
        # none, so non-rank-0 checkpoint reports are a supported shape the
        # salvage window must not drop (and when rank 0 IS the casualty,
        # the siblings' reports are all there is).
        # All polls submitted up front, then collected against ONE shared
        # deadline: the RPCs run concurrently, so a gang with several
        # unreachable members pays the deadline once, not per member. The
        # salvage pass sits between failure detection and abort_mesh(), so
        # it gets at most HALF the abort budget — the abort itself must
        # still fit in the rest.
        budget = self.run_config.failure_config.abort_deadline_s
        deadline = time.monotonic() + min(5.0, budget / 2)
        refs = [w.poll.remote(cursors[i]) for i, w in enumerate(wg.workers)]
        polls = []
        for ref in refs:
            try:
                res, _, _ = api.get(
                    ref, timeout=max(0.1, deadline - time.monotonic())
                )
            except Exception:  # noqa: BLE001 — this member is the casualty
                res = None
            polls.append(res)
        # Best-effort by contract: a checkpoint-registration failure here
        # must not replace the pending _WorkerGroupError (the caller raises
        # it right after this) — swallowing keeps the abort path intact.
        try:
            self._consume_batches(polls, history, offsets=cursors)
            if polls[0] is None:
                self._backfill_history(polls, cursors, history)
        except Exception as e:  # noqa: BLE001
            logging.getLogger(__name__).warning(
                "salvage drain failed, some final reports lost: %r", e
            )

    def _backfill_history(self, polls, cursors, history):
        """Rank 0 unreachable at salvage: extend history past rank 0's
        consumed prefix with the lowest surviving rank's entries for each
        missing absolute index (see _salvage_polls docstring). Checkpoints
        were already registered by _consume_batches's sibling scan."""
        by_abs: Dict[int, Any] = {}
        for i, res in enumerate(polls[1:], start=1):
            for j, entry in enumerate(res or ()):
                by_abs.setdefault(cursors[i] + j, entry)
        idx = cursors[0]  # rank 0's next-unconsumed absolute entry index
        while idx in by_abs:
            history.append(by_abs[idx]["metrics"])
            idx += 1

    def shutdown(self):
        if self._supervisor is not None:
            self._supervisor.stop_watch()
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None


class _WorkerGroupError(RuntimeError):
    # True when raised from _start_guarded: the gang never came up, so on
    # budget exhaustion with zero training progress the ORIGINAL exception
    # (an unsatisfiable ScalingConfig, a backend hook ImportError, ...) is
    # re-raised out of fit() instead of being folded into Result.error —
    # deterministic config errors must stay loud.
    during_start = False


def _shard_setter(name, shard):
    def setter():
        from .session import get_session

        s = get_session()
        if s is not None:
            s.context.dataset_shards[name] = shard
        return True

    return setter
