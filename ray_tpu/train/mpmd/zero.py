"""ZeRO-style sharded weight update over a stage's data-parallel replicas.

The ZeRO idea (arXiv 2004.13336): gradients REDUCE-SCATTER across the dp
group, each replica updates only its 1/dp chunk of the flat f32 optimizer
state (adam m/v + f32 master params), and the updated parameter chunks
ALL-GATHER back into the full working tree — optimizer memory per replica
drops ~dp x vs a replicated adamw, which is exactly the state that OOMs
first at GPT-J scale (MULTICHIP_GPTJ_r5.json had to drop dp entirely).

Layout contract: the flat space is chunked with np.array_split sizing
(`collective.ops.zero_shard_bounds`) — the SAME rule the host-plane
`collective.reduce_scatter_flat` uses for wire chunks and the elastic
checkpoint's axis-0 reshard applies on restore, so optimizer shards saved
at dp=4 restore as exactly the runtime chunks at dp=2.

Bit-parity contract: `ReplicatedAdamW` (the A/B baseline) reduces gradients
through the SAME reduce-scatter + all-gather pair before its full-width
update. AdamW is elementwise, so update-shard-then-gather and
gather-then-update produce bit-identical parameters — the parity gate in
tests/test_train_mpmd.py asserts exact equality, not allclose. Memory is
the only difference between the two paths.

Comm backends: `StoreDpComm` rides the host-plane object-store collectives
(separate replica processes — the DCN analog); `LocalDpComm` is an
in-process thread group for the parity tests and the local pipeline runner;
`SoloComm` is the dp=1 degenerate.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional

import numpy as np

from ...collective.ops import zero_shard_bounds


class SoloComm:
    """dp = 1: collectives are identity."""

    world = 1
    rank = 0

    def reduce_scatter_flat(self, vec: np.ndarray) -> np.ndarray:
        return np.array(np.asarray(vec).reshape(-1), copy=True)

    def all_gather_flat(self, chunk: np.ndarray) -> np.ndarray:
        return np.array(np.asarray(chunk).reshape(-1), copy=True)


class StoreDpComm:
    """Host-plane dp group between replica PROCESSES: wraps
    `ray_tpu.collective.{reduce_scatter_flat,all_gather_flat}` for one named
    group. The caller must have joined the group (init_collective_group) on
    the thread that runs the collectives."""

    def __init__(self, group_name: str, world: int, rank: int):
        self.group_name = group_name
        self.world = world
        self.rank = rank

    def reduce_scatter_flat(self, vec: np.ndarray) -> np.ndarray:
        from ... import collective

        return collective.reduce_scatter_flat(vec, group_name=self.group_name)

    def all_gather_flat(self, chunk: np.ndarray) -> np.ndarray:
        from ... import collective

        return collective.all_gather_flat(chunk, group_name=self.group_name)


class _LocalGroupState:
    """Shared rendezvous for an in-process dp group (threads)."""

    def __init__(self, world: int):
        self.world = world
        self.cond = threading.Condition()
        self.rounds: Dict[str, dict] = {}

    def exchange(self, key: str, rank: int, value, timeout: float = 60.0) -> List:
        """Deposit `value` for round `key`; block until every rank has;
        return values in rank order. The last rank to leave frees the
        round."""
        with self.cond:
            r = self.rounds.setdefault(key, {"vals": {}, "served": 0})
            r["vals"][rank] = value
            self.cond.notify_all()
            if not self.cond.wait_for(
                lambda: len(r["vals"]) >= self.world, timeout
            ):
                raise TimeoutError(f"local dp round {key} timed out")
            out = [r["vals"][k] for k in sorted(r["vals"])]
            r["served"] += 1
            if r["served"] >= self.world:
                self.rounds.pop(key, None)
            return out


class LocalDpComm:
    """In-process dp group member (one per replica thread)."""

    def __init__(self, state: _LocalGroupState, rank: int):
        self._state = state
        self.world = state.world
        self.rank = rank
        self._seq = 0

    def _next(self, tag: str) -> str:
        self._seq += 1
        return f"{tag}:{self._seq}"

    def reduce_scatter_flat(self, vec: np.ndarray) -> np.ndarray:
        vals = self._state.exchange(
            self._next("rs"), self.rank, np.asarray(vec).reshape(-1)
        )
        # Sorted-rank reduction order, matching the host plane's _reduce —
        # every rank computes bit-identical chunks.
        mine = [np.array_split(v, self.world)[self.rank] for v in vals]
        out = np.array(mine[0], copy=True)
        for m in mine[1:]:
            out = out + m
        return out

    def all_gather_flat(self, chunk: np.ndarray) -> np.ndarray:
        vals = self._state.exchange(
            self._next("ag"), self.rank, np.asarray(chunk).reshape(-1)
        )
        return np.concatenate(vals)


def make_local_comms(world: int) -> List[LocalDpComm]:
    state = _LocalGroupState(world)
    return [LocalDpComm(state, r) for r in range(world)]


# ----------------------------------------------------------------- optimizer
@functools.lru_cache(maxsize=None)
def _adamw_jit():
    import jax
    import jax.numpy as jnp

    def update(master, m, v, g, t, lr, b1, b2, eps, wd):
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * g * g
        mhat = m / (1.0 - b1 ** t)
        vhat = v / (1.0 - b2 ** t)
        step = lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * master)
        return master - step, m, v

    return jax.jit(update)


class _AdamWBase:
    def __init__(
        self,
        init_flat: np.ndarray,
        comm,
        lr: float = 1e-3,
        betas=(0.9, 0.95),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        self.comm = comm
        self.n = int(np.asarray(init_flat).reshape(-1).shape[0])
        self.lr, self.betas, self.eps, self.wd = lr, betas, eps, weight_decay
        self.t = 0

    def _update(self, master, m, v, g):
        self.t += 1
        return _adamw_jit()(
            master, m, v, g,
            np.float32(self.t), np.float32(self.lr),
            np.float32(self.betas[0]), np.float32(self.betas[1]),
            np.float32(self.eps), np.float32(self.wd),
        )

    def _reduced(self, local_grad_flat: np.ndarray) -> np.ndarray:
        """This rank's chunk of the dp-MEAN gradient (reduce-scatter sum,
        then / world) — the one reduction both paths share."""
        chunk = self.comm.reduce_scatter_flat(
            np.asarray(local_grad_flat, dtype=np.float32).reshape(-1)
        )
        if self.comm.world > 1:
            chunk = chunk / np.float32(self.comm.world)
        return chunk


class ShardedAdamW(_AdamWBase):
    """ZeRO path: optimizer state holds ONLY this rank's chunk."""

    def __init__(self, init_flat, comm, **kw):
        super().__init__(init_flat, comm, **kw)
        lo, hi = zero_shard_bounds(self.n, comm.world, comm.rank)
        self.bounds = (lo, hi)
        flat = np.asarray(init_flat, dtype=np.float32).reshape(-1)
        self.master = np.array(flat[lo:hi], copy=True)
        self.m = np.zeros(hi - lo, np.float32)
        self.v = np.zeros(hi - lo, np.float32)

    @property
    def optimizer_bytes(self) -> int:
        return self.master.nbytes + self.m.nbytes + self.v.nbytes

    def step(self, local_grad_flat: np.ndarray):
        """Returns (full updated flat params [n] f32, grad_sumsq of the
        dp-mean gradient — summed across chunks via a scalar gather so
        every rank reports the global value)."""
        g = self._reduced(local_grad_flat)
        master, m, v = self._update(self.master, self.m, self.v, g)
        self.master = np.asarray(master)
        self.m, self.v = np.asarray(m), np.asarray(v)
        full = self.comm.all_gather_flat(self.master)
        chunk_sq = float(np.sum(np.square(g, dtype=np.float64)))
        sumsq = float(
            np.sum(self.comm.all_gather_flat(np.array([chunk_sq], np.float32)))
        ) if self.comm.world > 1 else chunk_sq
        return full, sumsq

    # --------------------------------------------------------- checkpoint
    def ckpt_tree(self) -> Dict[str, np.ndarray]:
        """Axis-0-shardable state: each leaf is this rank's chunk, and the
        concatenation across ranks is the full flat space — exactly the
        shape `ShardedCheckpoint.restore`'s reshard rule redistributes on a
        dp change."""
        return {"master": self.master, "m": self.m, "v": self.v, }

    def load_ckpt_tree(self, tree: Dict[str, np.ndarray], t: int) -> None:
        lo, hi = self.bounds
        for name in ("master", "m", "v"):
            got = np.asarray(tree[name], dtype=np.float32).reshape(-1)
            if got.shape[0] != hi - lo:
                raise ValueError(
                    f"restored {name} chunk has {got.shape[0]} elements, "
                    f"rank {self.comm.rank}/{self.comm.world} owns {hi - lo}"
                )
            setattr(self, name, np.array(got, copy=True))
        self.t = int(t)

    def full_flat(self) -> np.ndarray:
        return self.comm.all_gather_flat(self.master)


class ReplicatedAdamW(_AdamWBase):
    """A/B baseline: every replica holds the FULL optimizer state. The
    gradient reduction is the same reduce-scatter + all-gather pair as the
    ZeRO path, so the two produce bit-identical parameters; per-replica
    optimizer memory (dp x larger) is the measured difference."""

    def __init__(self, init_flat, comm, **kw):
        super().__init__(init_flat, comm, **kw)
        self.master = np.array(
            np.asarray(init_flat, dtype=np.float32).reshape(-1), copy=True
        )
        self.m = np.zeros(self.n, np.float32)
        self.v = np.zeros(self.n, np.float32)

    @property
    def optimizer_bytes(self) -> int:
        return self.master.nbytes + self.m.nbytes + self.v.nbytes

    def step(self, local_grad_flat: np.ndarray):
        chunk = self._reduced(local_grad_flat)
        g = (
            self.comm.all_gather_flat(chunk)
            if self.comm.world > 1 else chunk
        )
        master, m, v = self._update(self.master, self.m, self.v, g)
        self.master = np.asarray(master)
        self.m, self.v = np.asarray(m), np.asarray(v)
        sumsq = float(np.sum(np.square(g, dtype=np.float64)))
        return np.array(self.master, copy=True), sumsq

    def ckpt_tree(self) -> Dict[str, np.ndarray]:
        return {"master": self.master, "m": self.m, "v": self.v}

    def load_ckpt_tree(self, tree: Dict[str, np.ndarray], t: int) -> None:
        for name in ("master", "m", "v"):
            got = np.asarray(tree[name], dtype=np.float32).reshape(-1)
            if got.shape[0] != self.n:
                raise ValueError(
                    f"restored {name} has {got.shape[0]} elements, "
                    f"model flat space has {self.n}"
                )
            setattr(self, name, np.array(got, copy=True))
        self.t = int(t)

    def full_flat(self) -> np.ndarray:
        return np.array(self.master, copy=True)
