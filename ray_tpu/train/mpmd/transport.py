"""Activation/gradient transport for the MPMD pipeline.

Control rides the compiled-DAG channels (`experimental/channel.py` shm
seqlock on a shared node, `tcp_channel.py` across nodes — the exact edges
`dag/compiled.py` builds); BULK tensor bytes ride the arena + bulk planes:
the sender lands the activation as a first-class arena object
(`put_serialized`, one out-of-band buffer at a knowable frame offset — the
PR 8/PR 10 span layout) and ships only a tiny descriptor through the
channel. The receiver imports by rung:

  1. inline — small tensors travel in the channel payload itself (the
     channels grow on demand, so this is a latency choice, not a limit);
  2. same-node — the descriptor names the segment in the shared store; the
     consumer deserializes straight off the arena mapping (zero RPCs, one
     memcpy into the consumer-owned array — the copy the device transfer
     would do anyway, taken eagerly so no view outlives the producer's pin);
  3. cross-node — `object_sources` resolves a live copy and
     `bulk.fetch_span_bytes` pulls exactly the tensor's span over the
     native off-GIL lander (one wire request, no whole-object get);
  4. no rung left -> the step fails loudly and the elastic layer owns it.

Pinning: the sender holds each published object's ref until the NEXT send
on the same edge completes. Channel writes block until the reader acked the
previous message, and the reader acks only after importing — so at the
moment a ref is dropped, its consumer is provably done with it.
"""

from __future__ import annotations

import pickle
import queue
from typing import Any, Dict, Optional

import numpy as np

DEFAULT_INLINE_MAX = 256 * 1024


def _rebuild_oob(dtype_str: str, shape, buf) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)


class _OOBArray:
    """Single-tensor analog of data/transport's _OOBColumn: the array's
    bytes travel as ONE out-of-band pickle-5 buffer at a computable frame
    offset; unpickling yields the ndarray directly."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray):
        self.arr = arr

    def __reduce__(self):
        return (
            _rebuild_oob,
            (self.arr.dtype.str, self.arr.shape, pickle.PickleBuffer(self.arr)),
        )


class ActTransport:
    """Publish/fetch of one tensor over the arena + bulk planes."""

    def __init__(
        self,
        inline_max_bytes: int = DEFAULT_INLINE_MAX,
        timeout_s: float = 120.0,
    ):
        self.inline_max = int(inline_max_bytes)
        self.timeout_s = timeout_s
        # Which rung each publish/fetch took — tests and the bench assert
        # the arena path actually engaged instead of trusting thresholds.
        self.stats = {
            "pub_inline": 0, "pub_arena": 0,
            "fetch_inline": 0, "fetch_local": 0, "fetch_span": 0,
        }

    # ----------------------------------------------------------- producer
    def publish(self, arr: np.ndarray):
        """Returns (desc, pin). `pin` (an ObjectRef or None) must be held by
        the caller until the consumer is done — the edge keeps it until its
        next send completes (see module docstring)."""
        from ...core import api, serialization, store

        arr = np.ascontiguousarray(arr)
        # _global_runtime (not the non-initializing peek): worker processes
        # build their runtime lazily on first API use, and a publish from a
        # stage actor's first step IS that first use.
        rt = api._global_runtime()
        backend = rt.backend if rt is not None else None
        put_serialized = getattr(backend, "put_serialized", None)
        # Below the store's own inline threshold put_serialized would land
        # the frame on the INLINE plane — no shared-store name, no
        # span-servable copy, nothing for fetch() to read — so such tensors
        # must stay inline in the channel regardless of inline_max.
        inline_floor = max(self.inline_max, store.INLINE_THRESHOLD)
        if (
            put_serialized is None
            or arr.nbytes <= inline_floor
            or getattr(backend, "remote_client", False)
        ):
            self.stats["pub_inline"] += 1
            return {"inline": arr}, None
        payload, buffers = serialization.serialize(_OOBArray(arr))
        if len(buffers) != 1:  # something unexpected went out-of-band
            self.stats["pub_inline"] += 1
            return {"inline": arr}, None
        try:
            task_hex = rt.current_task_id.hex()
        except Exception:  # noqa: BLE001 — outside a task context
            self.stats["pub_inline"] += 1
            return {"inline": arr}, None
        # Frame layout ([u32 npayload][payload][u32 nbufs]{[u64 len][bytes]})
        # puts the single buffer's data at a fixed offset.
        off = 4 + len(payload) + 4 + 8
        ref, name, span_ok = put_serialized(payload, buffers, task_hex)
        if name is None:
            # Inline/remote plane after all (threshold drift): the stored
            # object has no locally-readable name — keep the tensor in the
            # channel payload so the consumer never needs the object.
            self.stats["pub_inline"] += 1
            return {"inline": arr}, None
        desc = {
            "name": name,
            "hex": ref.id.hex(),
            "span": (off, arr.nbytes) if span_ok else None,
            "dtype": arr.dtype.str,
            "shape": tuple(arr.shape),
        }
        self.stats["pub_arena"] += 1
        return desc, ref

    # ----------------------------------------------------------- consumer
    def fetch(self, desc: Dict[str, Any]) -> np.ndarray:
        if "inline" in desc:
            self.stats["fetch_inline"] += 1
            return desc["inline"]
        from ...core import api
        from ...core import bulk as bulk_mod

        backend = api._global_runtime().backend
        # Rung 2: same-node shared-store read (the deps-map fast path's
        # equivalent — no controller round trip). Copy eagerly: the
        # unpickled array is a view over the producer's arena segment, and
        # nothing here may outlive the producer's pin.
        name = desc.get("name")
        local_store = getattr(backend, "local_store", None)
        if name and local_store is not None:
            try:
                out = np.array(local_store.read(name), copy=True)
            except Exception:  # noqa: BLE001 — not on this node / evicted
                pass
            else:
                # The copy is ours — release the read pin immediately, or
                # every per-microbatch activation object stays pinned in
                # this consumer process forever and the producer's drop
                # can never actually free arena space.
                try:
                    local_store.release(name)
                except Exception:  # noqa: BLE001 — release is best-effort
                    pass
                self.stats["fetch_local"] += 1
                return out
        # Rung 3: span pull over the bulk plane.
        span = desc.get("span")
        sources_of = getattr(backend, "object_sources", None)
        if span is not None and sources_of is not None:
            (src,) = sources_of([desc["hex"]])
            if src:
                off, length = span
                buf = bulk_mod.fetch_span_bytes(
                    src["bulk"], src["name"], off, length, self.timeout_s
                )
                self.stats["fetch_span"] += 1
                return np.frombuffer(
                    buf, dtype=np.dtype(desc["dtype"])
                ).reshape(desc["shape"])
        raise RuntimeError(
            f"activation object {desc.get('hex', '?')} unreachable "
            "(source gone and no span-servable copy) — failing the step for "
            "the elastic layer"
        )


class ChannelEdge:
    """One direction of one pipeline edge over a compiled-DAG channel.
    Construct with the writer end in the producer process and a reader-slot
    view in the consumer process (channels pickle-attach, exactly as
    compiled DAG arg plans ship them)."""

    def __init__(
        self,
        channel,
        transport: Optional[ActTransport] = None,
        timeout_s: float = 120.0,
    ):
        self._ch = channel
        self._transport = transport or ActTransport()
        self.timeout_s = timeout_s
        self._pin = None  # previous send's arena object, held until acked

    def send(self, arr: np.ndarray) -> None:
        desc, pin = self._transport.publish(np.asarray(arr))
        self._ch.write(desc, timeout=self.timeout_s)
        # write() returned => the reader acked the PREVIOUS message, whose
        # import finished before its ack — the old pin is dead weight now.
        self._pin = pin

    def recv(self) -> np.ndarray:
        desc = self._ch.begin_read(timeout=self.timeout_s)
        try:
            return self._transport.fetch(desc)
        finally:
            self._ch.end_read()

    def close(self) -> None:
        try:
            self._ch.close_writer()
        except Exception:  # noqa: BLE001
            pass
        self._pin = None


class LocalEdge:
    """In-process edge (thread-to-thread) with channel-like depth-1
    backpressure — the parity tests run the REAL 1F1B interleaving
    without a cluster."""

    def __init__(self, depth: int = 1, timeout_s: float = 60.0):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.timeout_s = timeout_s

    def send(self, arr: np.ndarray) -> None:
        self._q.put(np.asarray(arr), timeout=self.timeout_s)

    def recv(self) -> np.ndarray:
        return self._q.get(timeout=self.timeout_s)

    def close(self) -> None:
        pass
