"""Activation/gradient transport for the MPMD pipeline.

Control rides the compiled-DAG channels (`experimental/channel.py` shm
seqlock on a shared node, `tcp_channel.py` across nodes — the exact edges
`dag/compiled.py` builds); BULK tensor bytes ride the arena + bulk planes:
the sender lands the activation as a first-class arena object
(`put_serialized`, one out-of-band buffer at a knowable frame offset — the
PR 8/PR 10 span layout) and ships only a tiny descriptor through the
channel. The receiver imports by rung:

  1. inline — small tensors travel in the channel payload itself (the
     channels grow on demand, so this is a latency choice, not a limit);
  2. same-node — the descriptor names the segment in the shared store; the
     consumer deserializes straight off the arena mapping (zero RPCs, one
     memcpy into the consumer-owned array — the copy the device transfer
     would do anyway, taken eagerly so no view outlives the producer's pin);
  3. cross-node — `object_sources` resolves a live copy and
     `bulk.fetch_span_bytes` pulls exactly the tensor's span over the
     native off-GIL lander (one wire request, no whole-object get);
  4. no rung left -> the step fails loudly and the elastic layer owns it.

**Wire precision** (`wire_dtype`): with "bf16", f32 tensors are cast to
bfloat16 at publish (round-to-nearest-even via ml_dtypes — already a jax
dependency) and restored to f32 at fetch, halving every rung's bytes.
Master weights and the ZeRO update never see the wire dtype — only the
activation/grad hop is compressed. Default "f32" is a bit-exact identity
so the parity gates stay bitwise meaningful; bf16 is gated by an allclose
loss-curve test. `WireCodec.stats` counts raw vs wire bytes per frame so
benches and the perf smoke can assert the ~2x cut.

**Double-buffered sends** (`ChannelEdge(send_depth=2)`): publish stays on
the caller's thread, but the blocking channel write moves to a per-edge
sender thread behind a bounded ring — the send of microbatch k overlaps
the compute of k+1 instead of stalling on the reader's ack. Pinning
extends to a 2-deep ring: the sender holds each published object's ref
until the NEXT write on the same edge completes (write k returning means
the reader acked — finished importing — message k-1, so at most
`send_depth` pins are live). Deeper send buffering only RELAXES a
schedule proven deadlock-free at depth 1: every blocking wait that could
wedge happens strictly later, never earlier.
"""

from __future__ import annotations

import pickle
import queue
import threading
from typing import Any, Dict, Optional

import numpy as np

DEFAULT_INLINE_MAX = 256 * 1024

WIRE_DTYPES = ("f32", "bf16")


class WireCodec:
    """Optional lossy wire encoding for one pipeline hop. "f32" is the
    identity; "bf16" casts f32 arrays to bfloat16 for the wire (shipped as
    a u16 view — numpy has no native bfloat16 — and restored to f32 on the
    other side). Non-f32 arrays (tokens, already-bf16 payloads) pass
    through unchanged. Thread-safe byte counters in `.stats`."""

    def __init__(self, wire_dtype: str = "f32"):
        if wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of {WIRE_DTYPES}, got {wire_dtype!r}"
            )
        self.wire_dtype = wire_dtype
        self._lock = threading.Lock()
        self.stats = {"frames": 0, "raw_bytes": 0, "wire_bytes": 0}

    def encode(self, arr: np.ndarray):
        """-> (wire_arr, meta): meta is None for identity frames, else the
        original dtype str the decoder must restore."""
        arr = np.asarray(arr)
        out, meta = arr, None
        if self.wire_dtype == "bf16" and arr.dtype == np.float32:
            import ml_dtypes

            out = arr.astype(ml_dtypes.bfloat16).view(np.uint16)
            meta = arr.dtype.str
        with self._lock:
            self.stats["frames"] += 1
            self.stats["raw_bytes"] += arr.nbytes
            self.stats["wire_bytes"] += out.nbytes
        return out, meta

    def decode(self, arr: np.ndarray, meta: Optional[str]) -> np.ndarray:
        if meta is None:
            return arr
        import ml_dtypes

        return (
            np.asarray(arr).view(ml_dtypes.bfloat16).astype(np.dtype(meta))
        )


def _rebuild_oob(dtype_str: str, shape, buf) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)


class _OOBArray:
    """Single-tensor analog of data/transport's _OOBColumn: the array's
    bytes travel as ONE out-of-band pickle-5 buffer at a computable frame
    offset; unpickling yields the ndarray directly."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray):
        self.arr = arr

    def __reduce__(self):
        return (
            _rebuild_oob,
            (self.arr.dtype.str, self.arr.shape, pickle.PickleBuffer(self.arr)),
        )


class ActTransport:
    """Publish/fetch of one tensor over the arena + bulk planes."""

    def __init__(
        self,
        inline_max_bytes: int = DEFAULT_INLINE_MAX,
        timeout_s: float = 120.0,
        wire_dtype: str = "f32",
    ):
        self.inline_max = int(inline_max_bytes)
        self.timeout_s = timeout_s
        self.codec = WireCodec(wire_dtype)
        # Which rung each publish/fetch took — tests and the bench assert
        # the arena path actually engaged instead of trusting thresholds.
        # Wire bytes live in codec.stats and are merged into stats().
        self.stats = {
            "pub_inline": 0, "pub_arena": 0,
            "fetch_inline": 0, "fetch_local": 0, "fetch_span": 0,
        }

    def all_stats(self) -> Dict[str, int]:
        """Rung counters + the codec's raw/wire byte counters, one dict."""
        return {**self.stats, **self.codec.stats}

    # ----------------------------------------------------------- producer
    def publish(self, arr: np.ndarray):
        """Returns (desc, pin). `pin` (an ObjectRef or None) must be held by
        the caller until the consumer is done — the edge keeps it until its
        next send completes (see module docstring)."""
        from ...core import api, serialization, store

        arr, wire = self.codec.encode(np.ascontiguousarray(arr))
        arr = np.ascontiguousarray(arr)

        def inline_desc():
            d = {"inline": arr}
            if wire is not None:
                d["wire"] = wire
            return d

        # _global_runtime (not the non-initializing peek): worker processes
        # build their runtime lazily on first API use, and a publish from a
        # stage actor's first step IS that first use.
        rt = api._global_runtime()
        backend = rt.backend if rt is not None else None
        put_serialized = getattr(backend, "put_serialized", None)
        # Below the store's own inline threshold put_serialized would land
        # the frame on the INLINE plane — no shared-store name, no
        # span-servable copy, nothing for fetch() to read — so such tensors
        # must stay inline in the channel regardless of inline_max.
        inline_floor = max(self.inline_max, store.INLINE_THRESHOLD)
        if (
            put_serialized is None
            or arr.nbytes <= inline_floor
            or getattr(backend, "remote_client", False)
        ):
            self.stats["pub_inline"] += 1
            return inline_desc(), None
        payload, buffers = serialization.serialize(_OOBArray(arr))
        if len(buffers) != 1:  # something unexpected went out-of-band
            self.stats["pub_inline"] += 1
            return inline_desc(), None
        try:
            task_hex = rt.current_task_id.hex()
        except Exception:  # noqa: BLE001 — outside a task context
            self.stats["pub_inline"] += 1
            return inline_desc(), None
        # Frame layout ([u32 npayload][payload][u32 nbufs]{[u64 len][bytes]})
        # puts the single buffer's data at a fixed offset.
        off = 4 + len(payload) + 4 + 8
        ref, name, span_ok = put_serialized(payload, buffers, task_hex)
        if name is None:
            # Inline/remote plane after all (threshold drift): the stored
            # object has no locally-readable name — keep the tensor in the
            # channel payload so the consumer never needs the object.
            self.stats["pub_inline"] += 1
            return inline_desc(), None
        desc = {
            "name": name,
            "hex": ref.id.hex(),
            "span": (off, arr.nbytes) if span_ok else None,
            "dtype": arr.dtype.str,
            "shape": tuple(arr.shape),
        }
        if wire is not None:
            desc["wire"] = wire
        self.stats["pub_arena"] += 1
        return desc, ref

    # ----------------------------------------------------------- consumer
    def fetch(self, desc: Dict[str, Any]) -> np.ndarray:
        wire = desc.get("wire")
        if "inline" in desc:
            self.stats["fetch_inline"] += 1
            return self.codec.decode(desc["inline"], wire)
        from ...core import api
        from ...core import bulk as bulk_mod

        backend = api._global_runtime().backend
        # Rung 2: same-node shared-store read (the deps-map fast path's
        # equivalent — no controller round trip). Copy eagerly: the
        # unpickled array is a view over the producer's arena segment, and
        # nothing here may outlive the producer's pin.
        name = desc.get("name")
        local_store = getattr(backend, "local_store", None)
        if name and local_store is not None:
            try:
                out = np.array(local_store.read(name), copy=True)
            except Exception:  # noqa: BLE001 — not on this node / evicted
                pass
            else:
                # The copy is ours — release the read pin immediately, or
                # every per-microbatch activation object stays pinned in
                # this consumer process forever and the producer's drop
                # can never actually free arena space.
                try:
                    local_store.release(name)
                except Exception:  # noqa: BLE001 — release is best-effort
                    pass
                self.stats["fetch_local"] += 1
                return self.codec.decode(out, wire)
        # Rung 3: span pull over the bulk plane.
        span = desc.get("span")
        sources_of = getattr(backend, "object_sources", None)
        if span is not None and sources_of is not None:
            (src,) = sources_of([desc["hex"]])
            if src:
                off, length = span
                buf = bulk_mod.fetch_span_bytes(
                    src["bulk"], src["name"], off, length, self.timeout_s
                )
                self.stats["fetch_span"] += 1
                out = np.frombuffer(
                    buf, dtype=np.dtype(desc["dtype"])
                ).reshape(desc["shape"])
                return self.codec.decode(out, wire)
        raise RuntimeError(
            f"activation object {desc.get('hex', '?')} unreachable "
            "(source gone and no span-servable copy) — failing the step for "
            "the elastic layer"
        )


_RING_CLOSE = object()


class ChannelEdge:
    """One direction of one pipeline edge over a compiled-DAG channel.
    Construct with the writer end in the producer process and a reader-slot
    view in the consumer process (channels pickle-attach, exactly as
    compiled DAG arg plans ship them).

    `send_depth=1` keeps the classic synchronous write (send blocks until
    the reader acked the previous message). `send_depth>=2` moves the
    blocking write to a per-edge sender thread behind a ring of
    send_depth-1 queued messages + 1 in the write — the producer's compute
    overlaps the reader's ack. The pin contract extends with the ring: a
    published object's ref is dropped only after the NEXT write on this
    edge returns, so at most `send_depth` pins are live at once."""

    def __init__(
        self,
        channel,
        transport: Optional[ActTransport] = None,
        timeout_s: float = 120.0,
        send_depth: int = 1,
    ):
        self._ch = channel
        self._transport = transport or ActTransport()
        self.timeout_s = timeout_s
        self._depth = max(1, int(send_depth))
        self._pin = None  # previous send's arena object, held until acked
        self._ring: Optional["queue.Queue"] = None
        self._sender: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None

    def send(self, arr: np.ndarray) -> None:
        desc, pin = self._transport.publish(np.asarray(arr))
        if self._depth == 1:
            self._ch.write(desc, timeout=self.timeout_s)
            # write() returned => the reader acked the PREVIOUS message,
            # whose import finished before its ack — the old pin is dead
            # weight now.
            self._pin = pin
            return
        if self._err is not None:
            raise RuntimeError(
                f"pipeline edge sender failed: {self._err!r}"
            ) from self._err
        if self._ring is None:
            self._ring = queue.Queue(maxsize=self._depth - 1)
            self._sender = threading.Thread(
                target=self._drain, daemon=True, name="mpmd-edge-sender"
            )
            self._sender.start()
        try:
            self._ring.put((desc, pin), timeout=self.timeout_s)
        except queue.Full:
            raise RuntimeError(
                f"pipeline edge send ring full for {self.timeout_s:.0f}s "
                "(reader wedged?) — failing the step for the elastic layer"
            ) from None

    def _drain(self) -> None:
        prev_pin = None
        while True:
            item = self._ring.get()
            if item is _RING_CLOSE:
                break
            desc, pin = item
            try:
                self._ch.write(desc, timeout=self.timeout_s)
            except BaseException as e:  # noqa: BLE001 — surfaced on next send
                self._err = e
                break
            # This write returning means the reader acked the previous
            # message — ITS pin is droppable; the just-written message's
            # pin must survive until the next write returns.
            prev_pin = pin  # noqa: F841 — holding the ref IS the point
        self._pin = None

    def recv(self) -> np.ndarray:
        desc = self._ch.begin_read(timeout=self.timeout_s)
        try:
            return self._transport.fetch(desc)
        finally:
            self._ch.end_read()

    def close(self) -> None:
        if self._ring is not None:
            try:
                self._ring.put(_RING_CLOSE, timeout=5.0)
                self._sender.join(timeout=5.0)
            except Exception:  # noqa: BLE001 — best-effort drain
                pass
            self._ring = None
        try:
            self._ch.close_writer()
        except Exception:  # noqa: BLE001
            pass
        self._pin = None


class LocalEdge:
    """In-process edge (thread-to-thread) with channel-like depth-1
    backpressure — the parity tests run the REAL 1F1B interleaving
    without a cluster. Takes the same wire codec as the cluster path so
    the bf16 loss-curve gate exercises the actual cast/restore."""

    def __init__(
        self,
        depth: int = 1,
        timeout_s: float = 60.0,
        codec: Optional[WireCodec] = None,
    ):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.timeout_s = timeout_s
        self.codec = codec or WireCodec()

    def send(self, arr: np.ndarray) -> None:
        self._q.put(self.codec.encode(np.asarray(arr)), timeout=self.timeout_s)

    def recv(self) -> np.ndarray:
        wire_arr, meta = self._q.get(timeout=self.timeout_s)
        return self.codec.decode(wire_arr, meta)

    def close(self) -> None:
        pass
