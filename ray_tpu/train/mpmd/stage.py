"""StageRunner — one pipeline-stage replica's training loop body.

Process-agnostic: the cluster trainer hosts one of these per gang actor
(edges = compiled-DAG channels, comm = host-plane collectives), the local
runner hosts them on threads (queue edges, in-process comm). Each runner
owns ONE stage's jit programs — MPMD: S stages compile S different
programs, nothing here is shard_mapped over a pp axis.

Per step (`run_step`): execute the 1F1B op list; accumulate this replica's
stage gradients on device; then the ZeRO update — reduce-scatter the flat
gradient across the stage's dp group, update this replica's optimizer-state
chunk, all-gather the updated parameters (zero=False swaps in the
replicated-state baseline with the identical gradient reduction).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...collective.ops import zero_flatten, zero_unflatten
from ..elastic.state import ElasticState
from .schedule import B, F, build_1f1b
from .zero import ReplicatedAdamW, ShardedAdamW, SoloComm


@functools.lru_cache(maxsize=64)
def _jit_stage_fns(cfg, stage: int, num_stages: int) -> Dict[str, Any]:
    """Process-cached jitted stage programs: GPTConfig is a frozen
    (hashable) dataclass, so two runners for the same (cfg, stage, split)
    — a re-spawned incarnation, a second pipeline in the parity tests —
    share compilations instead of re-tracing fresh closures."""
    import jax

    from ...models import gpt

    fns = gpt.make_mpmd_stage_fns(cfg, stage, num_stages)
    return {name: jax.jit(fn) for name, fn in fns.items()}


@functools.lru_cache(maxsize=1)
def _acc_jit():
    import jax

    return jax.jit(
        lambda a, b: jax.tree_util.tree_map(lambda x, y: x + y, a, b)
    )


class StageRunner:
    def __init__(
        self,
        cfg,
        stage: int,
        num_stages: int,
        num_microbatches: int,
        stage_params,
        comm=None,
        *,
        replica: int = 0,
        zero: bool = True,
        lr: float = 1e-3,
        betas=(0.9, 0.95),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        import jax

        self.cfg = cfg
        self.stage = stage
        # dp-replica index — only used to label this runner's flight lane
        # and metric series; the comm object carries the collective rank.
        self.replica = replica
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.first = stage == 0
        self.last = stage == num_stages - 1
        self.comm = comm or SoloComm()
        self.zero = zero

        fns = _jit_stage_fns(cfg, stage, num_stages)
        self._fwd = fns["fwd"]
        self._fwd_bwd = fns.get("fwd_bwd")
        self._loss_bwd = fns.get("loss_bwd")
        self._acc = _acc_jit()

        flat, self._spec = zero_flatten(stage_params)
        opt_cls = ShardedAdamW if zero else ReplicatedAdamW
        self.opt = opt_cls(
            flat, self.comm, lr=lr, betas=betas, eps=eps,
            weight_decay=weight_decay,
        )
        self.params = jax.device_put(zero_unflatten(flat, self._spec))
        self.state = ElasticState()
        # Edges (bind_edges): None where the pipeline boundary is.
        self.fwd_in = self.fwd_out = self.bwd_in = self.bwd_out = None
        self.last_busy_s = 0.0
        self.last_update_s = 0.0

    # ---------------------------------------------------------------- wiring
    def bind_edges(self, fwd_in=None, fwd_out=None, bwd_in=None, bwd_out=None):
        self.fwd_in, self.fwd_out = fwd_in, fwd_out
        self.bwd_in, self.bwd_out = bwd_in, bwd_out

    # ------------------------------------------------------------------ step
    def run_step(self, tokens: Optional[np.ndarray]) -> Dict[str, Any]:
        """One training step over this replica's batch slice. `tokens`
        [b, S+1] feeds the first stage's inputs and the last stage's
        targets (both when S == 1); interior stages take None."""
        import jax
        import jax.numpy as jnp

        M = self.num_microbatches
        inputs = targets = None
        if self.first or self.last:
            if tokens is None:
                raise ValueError(
                    f"stage {self.stage} is a pipeline boundary and needs "
                    "the batch slice"
                )
            tokens = np.asarray(tokens)
            b = tokens.shape[0]
            if b % M != 0:
                raise ValueError(
                    f"replica batch {b} not divisible by {M} microbatches"
                )
            mb = b // M
            if self.first:
                inputs = tokens[:, :-1].reshape(M, mb, -1)
            if self.last:
                targets = tokens[:, 1:].reshape(M, mb, -1)

        from ...util import flight

        # Flight-recorder slot spans: a lane per (stage, dp-replica) and a
        # flow key per (step, microbatch, replica), so the merged Perfetto
        # view draws the 1F1B wave with arrows following each microbatch
        # across stages. Timing below uses monotonic_ns for BOTH the busy
        # accounting and the spans (one clock, one read per boundary);
        # recording is a lock-guarded list append (see overhead gate in
        # tests/test_flight_perf_smoke.py).
        fl = flight.recorder() if flight.enabled() else None
        if fl is not None:
            flight.ensure_flusher()
        lane = f"mpmd/s{self.stage}r{self.replica}"
        step_no = self.state.step + 1
        base = {"stage": self.stage, "replica": self.replica, "step": step_no}

        saved: Dict[int, Any] = {}
        acc = None
        losses: List[float] = []
        busy = 0.0
        for op, i in build_1f1b(self.stage, self.num_stages, M):
            flow = f"mb/{step_no}/{i}/r{self.replica}"
            if op == F:
                if self.first:
                    x = jnp.asarray(inputs[i])
                else:
                    r0 = time.monotonic_ns()
                    x = jnp.asarray(self.fwd_in.recv())
                    if fl is not None:
                        fl.record("mpmd.recv_wait", r0, time.monotonic_ns(),
                                  lane=lane,
                                  attrs={**base, "mb": i, "dir": "fwd"})
                saved[i] = x
                if not self.last:
                    t0 = time.monotonic_ns()
                    y = self._fwd(self.params, x)
                    y.block_until_ready()
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.fwd", t0, t1, lane=lane, flow=flow,
                                  attrs={**base, "mb": i})
                    s0 = time.monotonic_ns()
                    self.fwd_out.send(np.asarray(y))
                    if fl is not None:
                        fl.record("mpmd.send", s0, time.monotonic_ns(),
                                  lane=lane,
                                  attrs={**base, "mb": i, "dir": "fwd"})
                # Last stage: loss + backward run together at the B op.
            else:
                assert op == B
                x = saved.pop(i)
                if self.last:
                    t0 = time.monotonic_ns()
                    loss, gp, gx = self._loss_bwd(
                        self.params, x, jnp.asarray(targets[i])
                    )
                    jax.block_until_ready(gp)
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.bwd", t0, t1, lane=lane, flow=flow,
                                  attrs={**base, "mb": i})
                    losses.append(float(loss))
                else:
                    r0 = time.monotonic_ns()
                    gy = jnp.asarray(self.bwd_in.recv())
                    t0 = time.monotonic_ns()
                    gp, gx = self._fwd_bwd(self.params, x, gy)
                    jax.block_until_ready(gp)
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.recv_wait", r0, t0, lane=lane,
                                  attrs={**base, "mb": i, "dir": "bwd"})
                        fl.record("mpmd.bwd", t0, t1, lane=lane, flow=flow,
                                  attrs={**base, "mb": i})
                if not self.first:
                    s0 = time.monotonic_ns()
                    self.bwd_out.send(np.asarray(gx))
                    if fl is not None:
                        fl.record("mpmd.send", s0, time.monotonic_ns(),
                                  lane=lane,
                                  attrs={**base, "mb": i, "dir": "bwd"})
                acc = gp if acc is None else self._acc(acc, gp)

        # Mean over microbatches (loss = mean of equal-size microbatch
        # means), then the dp-sharded update.
        t0 = time.monotonic_ns()
        flat_g, _ = zero_flatten(jax.tree_util.tree_map(np.asarray, acc))
        flat_g = flat_g / np.float32(M)
        new_flat, grad_sumsq = self.opt.step(flat_g)
        self.params = jax.device_put(zero_unflatten(new_flat, self._spec))
        t1 = time.monotonic_ns()
        if fl is not None:
            fl.record("mpmd.update", t0, t1, lane=lane, attrs=dict(base))
        self.last_update_s = (t1 - t0) * 1e-9
        self.last_busy_s = busy
        try:
            from ...util.metrics import train_metrics

            train_metrics()["train_stage_step_seconds"].observe(
                busy + self.last_update_s,
                tags={"stage": str(self.stage),
                      "replica": str(self.replica)})
        except Exception:  # noqa: BLE001 — metrics must never fail a step
            pass
        self.state.step += 1
        out: Dict[str, Any] = {
            "step": self.state.step,
            "busy_s": busy,
            "update_s": self.last_update_s,
            "grad_sumsq": grad_sumsq,
            "opt_bytes": self.opt.optimizer_bytes,
        }
        if self.last:
            out["loss"] = float(np.mean(losses))
        return out

    # ------------------------------------------------------------ checkpoint
    def ckpt_tree(self) -> Dict[str, np.ndarray]:
        return self.opt.ckpt_tree()

    def load_ckpt(self, state: ElasticState, tree: Dict[str, np.ndarray]):
        """Adopt a restored optimizer shard (already resharded to this dp
        layout by ShardedCheckpoint.restore) and rebuild the working
        parameters from the gathered master chunks."""
        import jax

        self.state = state
        self.opt.load_ckpt_tree(tree, t=int(state.extra.get("opt_t", state.step)))
        self.params = jax.device_put(
            zero_unflatten(self.opt.full_flat(), self._spec)
        )

    def params_host(self):
        """Host copy of the full working parameters. Collective-free: the
        working tree is already the all-gathered result of the last update
        (calling into the optimizer here would be a stray collective that
        only one caller runs — a wedge)."""
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)
