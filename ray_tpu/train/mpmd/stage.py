"""StageRunner — one pipeline-stage replica's training loop body.

Process-agnostic: the cluster trainer hosts one of these per gang actor
(edges = compiled-DAG channels, comm = host-plane collectives), the local
runner hosts them on threads (queue edges, in-process comm). Each runner
owns ONE stage's jit programs — MPMD: S stages compile S different
programs, nothing here is shard_mapped over a pp axis. With interleaving
(num_chunks = v > 1) the runner owns v chunk programs (virtual stage
vs = c*S + s per chunk c) with per-(stage, chunk) jit cache entries and
per-chunk edges; all v chunk param trees live in ONE flat ZeRO space so
the sharded update is a single reduce-scatter/all-gather per step.

Per step (`run_step`): execute the (interleaved) 1F1B op list; accumulate
this replica's per-chunk gradients on device; reconcile the tied
embedding's gradient over the first/last-stage bridge if bound; then the
ZeRO update — reduce-scatter the flat gradient across the stage's dp
group, update this replica's optimizer-state chunk, all-gather the
updated parameters (zero=False swaps in the replicated-state baseline
with the identical gradient reduction).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...collective.ops import zero_flatten, zero_unflatten
from ..elastic.state import ElasticState
from .schedule import B, F, build_interleaved_1f1b
from .zero import ReplicatedAdamW, ShardedAdamW, SoloComm


@functools.lru_cache(maxsize=64)
def _jit_stage_fns(
    cfg, stage: int, num_stages: int, num_chunks: int = 1, chunk: int = 0
) -> Dict[str, Any]:
    """Process-cached jitted chunk programs: GPTConfig is a frozen
    (hashable) dataclass, so two runners for the same (cfg, stage, split,
    chunk) — a re-spawned incarnation, a second pipeline in the parity
    tests — share compilations instead of re-tracing fresh closures."""
    import jax

    from ...models import gpt

    fns = gpt.make_mpmd_stage_fns(
        cfg, stage, num_stages, num_chunks=num_chunks, chunk=chunk
    )
    return {name: jax.jit(fn) for name, fn in fns.items()}


@functools.lru_cache(maxsize=1)
def _acc_jit():
    import jax

    return jax.jit(
        lambda a, b: jax.tree_util.tree_map(lambda x, y: x + y, a, b)
    )


def _as_chunk_list(x, num_chunks: int) -> List[Any]:
    """Normalize an edge argument: None -> all-None, a single edge ->
    chunk 0 (the v=1 call shape), a list -> itself (must be length v)."""
    if x is None:
        return [None] * num_chunks
    if isinstance(x, (list, tuple)):
        if len(x) != num_chunks:
            raise ValueError(f"expected {num_chunks} edges, got {len(x)}")
        return list(x)
    if num_chunks != 1:
        raise ValueError("interleaved runners need per-chunk edge lists")
    return [x]


class StageRunner:
    def __init__(
        self,
        cfg,
        stage: int,
        num_stages: int,
        num_microbatches: int,
        stage_params,
        comm=None,
        *,
        replica: int = 0,
        num_chunks: int = 1,
        zero: bool = True,
        lr: float = 1e-3,
        betas=(0.9, 0.95),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        import jax

        self.cfg = cfg
        self.stage = stage
        # dp-replica index — only used to label this runner's flight lanes
        # and metric series; the comm object carries the collective rank.
        self.replica = replica
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.num_chunks = num_chunks
        # first/last mean "hosts the first/last VIRTUAL stage": chunk 0 of
        # stage 0 embeds tokens, chunk v-1 of stage S-1 computes the loss.
        self.first = stage == 0
        self.last = stage == num_stages - 1
        self.comm = comm or SoloComm()
        self.zero = zero
        # Validates (S, M, v) — incl. M % S == 0 for v > 1 — up front.
        self._ops = build_interleaved_1f1b(
            stage, num_stages, num_microbatches, num_chunks
        )

        self._fns = [
            _jit_stage_fns(cfg, stage, num_stages, num_chunks, c)
            for c in range(num_chunks)
        ]
        self._acc = _acc_jit()

        chunk_trees = (
            list(stage_params)
            if isinstance(stage_params, (list, tuple))
            else [stage_params]
        )
        if len(chunk_trees) != num_chunks:
            raise ValueError(
                f"stage {stage} got {len(chunk_trees)} chunk param trees, "
                f"expected {num_chunks}"
            )
        # ONE flat f32 space covering all chunks: v=1 keeps the bare tree
        # (flat layout — and so checkpoints — bit-identical to the
        # pre-interleaving code); v>1 namespaces chunks as {"c0": .., ..}.
        tree = (
            chunk_trees[0]
            if num_chunks == 1
            else {f"c{c}": t for c, t in enumerate(chunk_trees)}
        )
        flat, self._spec = zero_flatten(tree)
        opt_cls = ShardedAdamW if zero else ReplicatedAdamW
        self.opt = opt_cls(
            flat, self.comm, lr=lr, betas=betas, eps=eps,
            weight_decay=weight_decay,
        )
        self.params = jax.device_put(zero_unflatten(flat, self._spec))
        self.state = ElasticState()
        # Per-chunk edges (bind_edges): None where the virtual-stage chain
        # boundary is. The bridge pair reconciles the tied embedding grad.
        self.fwd_in = [None] * num_chunks
        self.fwd_out = [None] * num_chunks
        self.bwd_in = [None] * num_chunks
        self.bwd_out = [None] * num_chunks
        self.bridge_out = self.bridge_in = None
        self.last_busy_s = 0.0
        self.last_update_s = 0.0

    # ---------------------------------------------------------------- wiring
    def bind_edges(
        self, fwd_in=None, fwd_out=None, bwd_in=None, bwd_out=None,
        bridge_out=None, bridge_in=None,
    ):
        v = self.num_chunks
        self.fwd_in = _as_chunk_list(fwd_in, v)
        self.fwd_out = _as_chunk_list(fwd_out, v)
        self.bwd_in = _as_chunk_list(bwd_in, v)
        self.bwd_out = _as_chunk_list(bwd_out, v)
        self.bridge_out, self.bridge_in = bridge_out, bridge_in

    def _chunk_params(self, c: int):
        return self.params if self.num_chunks == 1 else self.params[f"c{c}"]

    # ------------------------------------------------------------------ step
    def run_step(self, tokens: Optional[np.ndarray]) -> Dict[str, Any]:
        """One training step over this replica's batch slice. `tokens`
        [b, S+1] feeds the first stage's inputs and the last stage's
        targets (both when S == 1); interior stages take None."""
        import jax
        import jax.numpy as jnp

        M, v, S = self.num_microbatches, self.num_chunks, self.num_stages
        P = S * v
        inputs = targets = None
        if self.first or self.last:
            if tokens is None:
                raise ValueError(
                    f"stage {self.stage} is a pipeline boundary and needs "
                    "the batch slice"
                )
            tokens = np.asarray(tokens)
            b = tokens.shape[0]
            if b % M != 0:
                raise ValueError(
                    f"replica batch {b} not divisible by {M} microbatches"
                )
            mb = b // M
            if self.first:
                inputs = tokens[:, :-1].reshape(M, mb, -1)
            if self.last:
                targets = tokens[:, 1:].reshape(M, mb, -1)

        from ...util import flight

        # Flight-recorder slot spans: a lane per (stage, chunk, dp-replica)
        # — interleaved chunks render on separate Perfetto rows instead of
        # shuffling two chunks' spans on one — and a flow key per (step,
        # microbatch, chunk, replica), so the merged view draws the 1F1B
        # wave with arrows following each microbatch across stages.
        # `pipeline_report` regroups these lanes by PHYSICAL (stage,
        # replica) attrs so its bubble denominator stays wall*S*dp, the
        # same as the trainer's aggregate. Timing below uses monotonic_ns
        # for BOTH the busy accounting and the spans (one clock, one read
        # per boundary); recording is a lock-guarded list append (see
        # overhead gate in tests/test_flight_perf_smoke.py).
        fl = flight.recorder() if flight.enabled() else None
        if fl is not None:
            flight.ensure_flusher()
        lanes = [
            f"mpmd/s{self.stage}c{c}r{self.replica}" for c in range(v)
        ]
        step_no = self.state.step + 1
        base = {"stage": self.stage, "replica": self.replica, "step": step_no}

        saved: Dict[tuple, Any] = {}
        accs: List[Any] = [None] * v
        losses: List[float] = []
        busy = 0.0
        for op, i, c in self._ops:
            vs = c * S + self.stage
            firstc, lastc = vs == 0, vs == P - 1
            lane = lanes[c]
            flow = f"mb/{step_no}/{i}/c{c}/r{self.replica}"
            attrs = {**base, "mb": i, "chunk": c}
            fns = self._fns[c]
            if op == F:
                if firstc:
                    x = jnp.asarray(inputs[i])
                else:
                    r0 = time.monotonic_ns()
                    x = jnp.asarray(self.fwd_in[c].recv())
                    if fl is not None:
                        fl.record("mpmd.recv_wait", r0, time.monotonic_ns(),
                                  lane=lane, attrs={**attrs, "dir": "fwd"})
                saved[(c, i)] = x
                if not lastc:
                    t0 = time.monotonic_ns()
                    y = fns["fwd"](self._chunk_params(c), x)
                    y.block_until_ready()
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.fwd", t0, t1, lane=lane, flow=flow,
                                  attrs=attrs)
                    s0 = time.monotonic_ns()
                    self.fwd_out[c].send(np.asarray(y))
                    if fl is not None:
                        fl.record("mpmd.send", s0, time.monotonic_ns(),
                                  lane=lane, attrs={**attrs, "dir": "fwd"})
                # Last virtual stage: loss + backward run at the B op.
            else:
                assert op == B
                x = saved.pop((c, i))
                if lastc:
                    t0 = time.monotonic_ns()
                    loss, gp, gx = fns["loss_bwd"](
                        self._chunk_params(c), x, jnp.asarray(targets[i])
                    )
                    jax.block_until_ready(gp)
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.bwd", t0, t1, lane=lane, flow=flow,
                                  attrs=attrs)
                    losses.append(float(loss))
                else:
                    r0 = time.monotonic_ns()
                    gy = jnp.asarray(self.bwd_in[c].recv())
                    t0 = time.monotonic_ns()
                    gp, gx = fns["fwd_bwd"](self._chunk_params(c), x, gy)
                    jax.block_until_ready(gp)
                    t1 = time.monotonic_ns()
                    busy += (t1 - t0) * 1e-9
                    if fl is not None:
                        fl.record("mpmd.recv_wait", r0, t0, lane=lane,
                                  attrs={**attrs, "dir": "bwd"})
                        fl.record("mpmd.bwd", t0, t1, lane=lane, flow=flow,
                                  attrs=attrs)
                if not firstc:
                    s0 = time.monotonic_ns()
                    self.bwd_out[c].send(np.asarray(gx))
                    if fl is not None:
                        fl.record("mpmd.send", s0, time.monotonic_ns(),
                                  lane=lane, attrs={**attrs, "dir": "bwd"})
                accs[c] = gp if accs[c] is None else self._acc(accs[c], gp)

        # Tied-embedding bridge (Megatron embedding allreduce): tok_embed
        # lives on virtual stage 0 (chunk 0 here if stage 0) AND virtual
        # stage P-1 (chunk v-1 if stage S-1); each side contributes a
        # partial gradient. Exchange the two partials over the dedicated
        # edge pair and SUM — float addition commutes, so both hosts
        # compute bit-identical totals and (same init, same elementwise
        # adamw) the two copies stay bit-identical forever. Send-then-recv
        # is deadlock-free: the directions are separate depth-1 channels
        # and each carries exactly one message per step.
        if self.bridge_out is not None:
            own = 0 if self.first else v - 1
            acc_np = {
                k: np.asarray(g)
                for k, g in accs[own].items()
            } if isinstance(accs[own], dict) else accs[own]
            mine = np.asarray(acc_np["tok_embed"], dtype=np.float32)
            b0 = time.monotonic_ns()
            self.bridge_out.send(mine)
            other = np.asarray(self.bridge_in.recv(), dtype=np.float32)
            if fl is not None:
                fl.record(
                    "mpmd.bridge", b0, time.monotonic_ns(),
                    lane=lanes[own],
                    attrs={**base, "chunk": own, "dir": "embed"},
                )
            acc_np["tok_embed"] = mine + other
            accs[own] = acc_np

        # Mean over microbatches (loss = mean of equal-size microbatch
        # means), then the dp-sharded update over the ONE flat space.
        t0 = time.monotonic_ns()
        acc = accs[0] if v == 1 else {f"c{c}": accs[c] for c in range(v)}
        flat_g, _ = zero_flatten(jax.tree_util.tree_map(np.asarray, acc))
        flat_g = flat_g / np.float32(M)
        new_flat, grad_sumsq = self.opt.step(flat_g)
        self.params = jax.device_put(zero_unflatten(new_flat, self._spec))
        t1 = time.monotonic_ns()
        if fl is not None:
            fl.record("mpmd.update", t0, t1, lane=lanes[0],
                      attrs=dict(base))
        self.last_update_s = (t1 - t0) * 1e-9
        self.last_busy_s = busy
        try:
            from ...util.metrics import train_metrics

            train_metrics()["train_stage_step_seconds"].observe(
                busy + self.last_update_s,
                tags={"stage": str(self.stage),
                      "replica": str(self.replica)})
        except Exception:  # noqa: BLE001 — metrics must never fail a step
            pass
        self.state.step += 1
        out: Dict[str, Any] = {
            "step": self.state.step,
            "busy_s": busy,
            "update_s": self.last_update_s,
            "grad_sumsq": grad_sumsq,
            "opt_bytes": self.opt.optimizer_bytes,
        }
        if self.last:
            out["loss"] = float(np.mean(losses))
        return out

    # ------------------------------------------------------------ checkpoint
    def ckpt_tree(self) -> Dict[str, np.ndarray]:
        return self.opt.ckpt_tree()

    def load_ckpt(self, state: ElasticState, tree: Dict[str, np.ndarray]):
        """Adopt a restored optimizer shard (already resharded to this dp
        layout by ShardedCheckpoint.restore) and rebuild the working
        parameters from the gathered master chunks."""
        import jax

        self.state = state
        self.opt.load_ckpt_tree(tree, t=int(state.extra.get("opt_t", state.step)))
        self.params = jax.device_put(
            zero_unflatten(self.opt.full_flat(), self._spec)
        )

    def params_host(self):
        """Host copy of the full working parameters (the chunk-namespaced
        tree when interleaved). Collective-free: the working tree is
        already the all-gathered result of the last update (calling into
        the optimizer here would be a stray collective that only one
        caller runs — a wedge)."""
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def chunk_params_host(self, c: int):
        """Host copy of ONE chunk's param tree (the whole tree at v=1)."""
        import jax

        return jax.tree_util.tree_map(np.asarray, self._chunk_params(c))
