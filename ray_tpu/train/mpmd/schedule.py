"""1F1B pipeline schedules (host-side, per stage): classic and interleaved.

The MPMD pipeline runs the classic one-forward-one-backward order
(PipeDream-flush / Megatron "1F1B"): stage s of S warms up with
min(M, S-1-s) forwards, then alternates F/B in steady state, then drains
the remaining backwards. Peak in-flight microbatches at stage s is
S - s (vs M for GPipe), which is what bounds the saved-activation memory —
the runner stores only each in-flight microbatch's stage INPUT and
recomputes the forward inside backward (`models/gpt.make_mpmd_stage_fns`).

**Interleaved (virtual-stage) 1F1B** (Megatron interleaving, arXiv
2410.06511 shape): each physical stage holds v model CHUNKS instead of one
contiguous slice — chunk c of stage s is virtual stage vs = c*S + s of
P = S*v, so the model wraps around the physical ring v times. Warmup grows
to min(M*v, (v-1)*S + 2*(S-1-s)) forwards taken in virtual-stage-major
order (S consecutive microbatches through chunk 0, the same S through
chunk 1, ...), then steady state alternates F/B with the same rotation on
both directions, then the backward drain. The bubble shrinks because the
warmup/drain ramps are per-CHUNK (depth 1/v of the model each) while the
steady region covers v*M ops: the ideal fraction drops from
(S-1)/(M+S-1) to (S-1)/(v*M + S-1). The price is a longer in-flight
window: peak saved stage-inputs at stage s become min(M*v, warmup+1)
(each saved input is 1/v of the v=1 activation depth, so memory stays
comparable; exact bound asserted across an (S, M, v) grid in
tests/test_train_mpmd.py).

The schedule is a plain per-stage op list computed up front: deterministic,
no cross-host coordination beyond the activation/grad channels themselves.
With depth-1 channels (the compiled-DAG seqlock edges) the interleaving is
deadlock-free: a virtual stage's k-th write is acked by its consumer's k-th
read, and the op order makes every recv depend only on ops EARLIER in the
producing neighbor's own list — for v>1 this needs M % S == 0 (each
warmup group feeds the next chunk exactly when its S-microbatch wave
arrives; a partial wave would leave a chunk-(c+1) recv waiting on a
chunk-c forward scheduled after it). The property test simulates every
stage's list against blocking depth-1 channels across the grid.
"""

from __future__ import annotations

from typing import List, Tuple

# Op kinds: ("F", mb, chunk) = forward microbatch mb through model chunk
# `chunk` (recv activation / take input slice, compute, send to the next
# virtual stage); ("B", mb, chunk) = backward (recv grad / compute loss
# grad, compute, send upstream, accumulate). `build_1f1b` keeps the
# classic 2-tuple form for v=1 callers.
F = "F"
B = "B"


def build_1f1b(stage: int, num_stages: int, num_microbatches: int) -> List[Tuple[str, int]]:
    """The classic (v=1) op sequence stage `stage` executes for one step."""
    S, M, s = num_stages, num_microbatches, stage
    if not 0 <= s < S:
        raise ValueError(f"stage {s} out of range for {S} stages")
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    warmup = min(M, S - 1 - s)
    ops: List[Tuple[str, int]] = [(F, i) for i in range(warmup)]
    f, b = warmup, 0
    while f < M or b < M:
        if f < M:
            ops.append((F, f))
            f += 1
        if b < M:
            ops.append((B, b))
            b += 1
    return ops


def build_interleaved_1f1b(
    stage: int, num_stages: int, num_microbatches: int, num_chunks: int = 1
) -> List[Tuple[str, int, int]]:
    """The op sequence stage `stage` executes with v model chunks per
    stage (ops are (F|B, microbatch, chunk)). v=1 reproduces `build_1f1b`
    exactly (with chunk 0 appended); v>1 is the Megatron interleaved
    order and requires num_microbatches % num_stages == 0 (see module
    docstring) and num_stages > 1 (a single stage has nothing to
    interleave across — its "wrap" edges would be self-loops)."""
    S, M, v, s = num_stages, num_microbatches, num_chunks, stage
    if v < 1:
        raise ValueError(f"num_chunks must be >= 1, got {v}")
    if v == 1:
        return [(op, mb, 0) for op, mb in build_1f1b(s, S, M)]
    if S == 1:
        raise ValueError(
            "interleaved schedule needs num_stages > 1 when num_chunks > 1 "
            "(chunk-to-chunk edges on one stage would be self-loops)"
        )
    if not 0 <= s < S:
        raise ValueError(f"stage {s} out of range for {S} stages")
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    if M % S != 0:
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches % num_stages == 0 "
            f"(got M={M}, S={S}): warmup feeds chunks in waves of S "
            "microbatches and a partial wave deadlocks depth-1 channels"
        )
    total = M * v
    warmup = min(total, (v - 1) * S + 2 * (S - 1 - s))

    # k-th forward/backward in virtual-stage-major rotation: groups of
    # S*v ops; within a group, S consecutive microbatches through each
    # chunk in turn (forward ascends chunks, backward descends).
    def fwd_k(k: int) -> Tuple[int, int]:
        g, r = divmod(k, S * v)
        return g * S + r % S, r // S

    def bwd_k(k: int) -> Tuple[int, int]:
        g, r = divmod(k, S * v)
        return g * S + r % S, v - 1 - r // S

    ops: List[Tuple[str, int, int]] = [(F, *fwd_k(k)) for k in range(warmup)]
    f, b = warmup, 0
    while f < total or b < total:
        if f < total:
            ops.append((F, *fwd_k(f)))
            f += 1
        if b < total:
            ops.append((B, *bwd_k(b)))
            b += 1
    return ops


def max_in_flight(
    stage: int, num_stages: int, num_microbatches: int, num_chunks: int = 1
) -> int:
    """Peak number of (microbatch, chunk) stage inputs saved at once — the
    1F1B memory bound. v=1: min(M, S - stage). v>1: warmup+1 capped at
    M*v (the +1 is the steady state's one extra forward in flight before
    each backward retires one); each saved input spans 1/v of the v=1
    chunk depth, so the BYTES bound stays the same order."""
    S, M, v, s = num_stages, num_microbatches, num_chunks, stage
    if v == 1:
        return min(M, S - s)
    warmup = min(M * v, (v - 1) * S + 2 * (S - 1 - s))
    return min(M * v, warmup + 1)


def theoretical_bubble_fraction(
    num_stages: int, num_microbatches: int, num_chunks: int = 1
) -> float:
    """Ideal pipeline bubble for equal-cost stages:
    (S-1) / (v*M + S - 1) — interleaving divides the warmup/drain ramp
    depth by v while the steady region keeps v*M ops per stage."""
    S, M, v = num_stages, num_microbatches, num_chunks
    return (S - 1) / (v * M + S - 1)
