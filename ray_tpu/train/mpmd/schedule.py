"""1F1B pipeline schedule (host-side, per stage).

The MPMD pipeline runs the classic one-forward-one-backward order
(PipeDream-flush / Megatron "1F1B"): stage s of S warms up with
min(M, S-1-s) forwards, then alternates F/B in steady state, then drains
the remaining backwards. Peak in-flight microbatches at stage s is
S - s (vs M for GPipe), which is what bounds the saved-activation memory —
the runner stores only each in-flight microbatch's stage INPUT and
recomputes the forward inside backward (`models/gpt.make_mpmd_stage_fns`).

The schedule is a plain per-stage op list computed up front: deterministic,
no cross-host coordination beyond the activation/grad channels themselves.
With depth-1 channels (the compiled-DAG seqlock edges) the interleaving is
deadlock-free: a stage's k-th write is acked by the consumer's k-th read,
and 1F1B orders every stage's reads/writes so each blocks only on work the
neighbor performs earlier in its own list (exercised across (S, M) shapes
in tests/test_train_mpmd.py).
"""

from __future__ import annotations

from typing import List, Tuple

# Op kinds: ("F", mb) = forward microbatch mb (recv activation / take input
# slice, compute, send downstream); ("B", mb) = backward microbatch mb
# (recv grad / compute loss grad, compute, send upstream, accumulate).
F = "F"
B = "B"


def build_1f1b(stage: int, num_stages: int, num_microbatches: int) -> List[Tuple[str, int]]:
    """The op sequence stage `stage` executes for one training step."""
    S, M, s = num_stages, num_microbatches, stage
    if not 0 <= s < S:
        raise ValueError(f"stage {s} out of range for {S} stages")
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    warmup = min(M, S - 1 - s)
    ops: List[Tuple[str, int]] = [(F, i) for i in range(warmup)]
    f, b = warmup, 0
    while f < M or b < M:
        if f < M:
            ops.append((F, f))
            f += 1
        if b < M:
            ops.append((B, b))
            b += 1
    return ops


def max_in_flight(stage: int, num_stages: int, num_microbatches: int) -> int:
    """Peak number of microbatches whose stage input is saved at once —
    the 1F1B memory bound (min(M, S - stage))."""
    return min(num_microbatches, num_stages - stage)


def theoretical_bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Ideal pipeline bubble for equal-cost stages: (S-1) / (M + S - 1)."""
    S, M = num_stages, num_microbatches
    return (S - 1) / (M + S - 1)
