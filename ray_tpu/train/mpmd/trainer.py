"""MPMDTrainer — the cluster MPMD pipeline, composed with elastic.

Topology: S stages x dp replicas = S*dp gang actors. Replica r of stage s
pipes activations to replica r of stage s+1 (and grads back) over
compiled-DAG edge channels (`dag.compiled.make_edge_channel`: shm seqlock
on a shared node, persistent TCP across nodes), with bulk tensors riding
arena segments + span pulls (`mpmd.transport`). Each stage's dp replicas
form one host-plane collective group for the ZeRO update.

Elastic composition (the PR 4 machinery, extended):
  * the GangSupervisor watches ALL S*dp actors through the controller death
    feed; any member death (or a failed step RPC) aborts the WHOLE mesh —
    every stage collective group is aborted so no survivor waits out a
    rendezvous round on a dead peer, then the actors are killed and the
    channels destroyed;
  * the restart policy (budget + backoff) is the supervisor's; after the
    backoff the pipeline RESHAPES: dp is re-picked from currently-feasible
    capacity within [dp_min, dp_max] (stage count S is fixed — stage splits
    cannot change across a reshape, see ElasticState.check_pipeline);
  * stage-local checkpoint shards (`elastic.stage_root` layout, one
    AsyncShardWriter per replica with world=dp) restore at the pipeline's
    COMMON committed step (`latest_common_committed`), resharding each
    stage's flat optimizer chunks across the new dp width with the existing
    axis-0 machinery. The step counter continues exactly where the commit
    left it.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ...dag.compiled import ChannelHostMixin
from ..config import FailureConfig, RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


@dataclass
class MPMDOptions:
    num_stages: int = 2
    dp: int = 1
    dp_min: Optional[int] = None      # elasticity band for reshapes
    dp_max: Optional[int] = None
    num_microbatches: int = 2
    num_chunks: int = 1               # v model chunks per stage (interleaved
                                      # 1F1B; v>1 needs M % S == 0)
    wire_dtype: str = "f32"           # activation/grad wire: "f32" | "bf16"
    send_depth: int = 2               # per-edge send ring (1 = synchronous)
    zero: bool = True                 # ZeRO sharded update vs replicated
    lr: float = 1e-3
    betas: tuple = (0.9, 0.95)
    eps: float = 1e-8
    weight_decay: float = 0.0
    seed: int = 0
    ckpt_every: int = 1               # steps between async stage saves
    step_timeout_s: float = 120.0     # driver-side deadline per step RPC
    channel_timeout_s: float = 120.0  # edge send/recv deadline in the actors
    inline_max_bytes: int = 256 * 1024
    channel_buffer_bytes: int = 1 << 20
    num_cpus_per_replica: float = 1.0

    def band(self) -> "tuple[int, int]":
        hi = self.dp if self.dp_max is None else self.dp_max
        lo = hi if self.dp_min is None else self.dp_min
        return max(1, min(lo, hi)), hi


class _StageReplica(ChannelHostMixin):
    """Gang actor hosting one (stage, dp-rank) StageRunner. The channel
    construction surface (node_id/bind_tcp_channel/create_shm_channel)
    comes from the compiled-DAG mixin so `make_edge_channel` binds edges in
    this process exactly as it does for DAG stage hosts."""

    def __init__(self, payload: bytes):
        import cloudpickle

        self._opts = cloudpickle.loads(payload)
        self._runner = None
        self._writer = None

    def pid(self) -> int:
        import os

        return os.getpid()

    def setup(self, edges_payload: bytes, restore_step: Optional[int]) -> int:
        """Join the stage dp group, build the runner, bind edges, restore.
        Returns the step to resume from (0 on a fresh run)."""
        import cloudpickle

        from ... import collective
        from ..elastic import AsyncShardWriter, ShardedCheckpoint
        from .stage import StageRunner
        from .transport import ActTransport, ChannelEdge
        from .zero import SoloComm, StoreDpComm

        o = self._opts
        edges = cloudpickle.loads(edges_payload)
        if o["dp"] > 1:
            collective.init_collective_group(
                o["dp"], o["dp_rank"], group_name=o["group_name"]
            )
            comm = StoreDpComm(o["group_name"], o["dp"], o["dp_rank"])
        else:
            comm = SoloComm()
        cfg = o["cfg"]
        v = o.get("num_chunks", 1)
        # Only THIS stage's parameter slices ever land in this process —
        # the driver initialized the full tree once and shipped slices.
        self._runner = StageRunner(
            cfg, o["stage"], o["num_stages"], o["num_microbatches"],
            o["stage_params"], comm, replica=o["dp_rank"],
            num_chunks=v, zero=o["zero"],
            lr=o["lr"], betas=o["betas"], eps=o["eps"],
            weight_decay=o["weight_decay"],
        )
        transport = ActTransport(
            inline_max_bytes=o["inline_max_bytes"],
            timeout_s=o["channel_timeout_s"],
            wire_dtype=o.get("wire_dtype", "f32"),
        )
        self._transport = transport
        # The bridge carries gradients FOR the update — it never rides the
        # lossy wire, so it gets its own f32 transport.
        bridge_transport = ActTransport(
            inline_max_bytes=o["inline_max_bytes"],
            timeout_s=o["channel_timeout_s"],
        )

        def edge(ch, tr=transport):
            return (
                ChannelEdge(
                    ch, tr, timeout_s=o["channel_timeout_s"],
                    send_depth=o.get("send_depth", 1),
                )
                if ch is not None else None
            )

        def chunk_edges(key):
            chs = edges.get(key) or [None] * v
            return [edge(ch) for ch in chs]

        self._runner.bind_edges(
            fwd_in=chunk_edges("fwd_in"),
            fwd_out=chunk_edges("fwd_out"),
            bwd_in=chunk_edges("bwd_in"),
            bwd_out=chunk_edges("bwd_out"),
            bridge_out=edge(edges.get("bridge_out"), bridge_transport),
            bridge_in=edge(edges.get("bridge_in"), bridge_transport),
        )
        self._writer = AsyncShardWriter(
            o["stage_root"], o["dp_rank"], o["dp"], gen=o["gen"],
            mode="sharded" if o["zero"] else "replicated",
        )
        if restore_step is not None:
            found = ShardedCheckpoint.restore(
                o["stage_root"], o["dp_rank"], o["dp"], step=restore_step
            )
            if found is None:
                raise RuntimeError(
                    f"stage {o['stage']} rank {o['dp_rank']}: committed "
                    f"step {restore_step} vanished before restore"
                )
            state, tree = found
            state.check_pipeline(
                o["stage"], o["num_stages"], o.get("num_chunks", 1)
            )
            self._runner.load_ckpt(state, tree)
        return self._runner.state.step

    def run_step(self, tokens: Optional[np.ndarray], save: bool) -> Dict[str, Any]:
        o = self._opts
        metrics = self._runner.run_step(tokens)
        if save:
            st = self._runner.state
            st.record_pipeline(
                o["stage"], o["num_stages"], o.get("num_chunks", 1)
            )
            st.extra["opt_t"] = self._runner.opt.t
            self._writer.save(st.step, self._runner.ckpt_tree(), st)
        return metrics

    def flush(self, timeout: float = 60.0) -> bool:
        return self._writer.flush(timeout) if self._writer is not None else True

    def transport_stats(self) -> Dict[str, int]:
        t = getattr(self, "_transport", None)
        return t.all_stats() if t is not None else {}


class _MPMDGang:
    """The supervisor-facing gang shim: S*dp actors + their edges/groups."""

    def __init__(self, actors, channels, groups):
        self.actors = actors            # {(stage, rank): handle}
        self.channels = channels
        self.groups = groups

    def actor_ids(self) -> List[str]:
        return [a._id.hex() for a in self.actors.values()]

    def shutdown(self):
        from ...core import api
        from ... import collective

        for g in self.groups:
            try:
                collective.abort_collective_group(g, timeout=5.0)
            except Exception:  # noqa: BLE001
                pass
        for a in self.actors.values():
            try:
                api.kill(a)
            except Exception:  # noqa: BLE001
                pass
        for g in self.groups:
            try:
                collective.destroy_collective_group(g)
            except Exception:  # noqa: BLE001
                pass
        for ch in self.channels:
            try:
                ch.destroy()
            except Exception:  # noqa: BLE001
                pass


class MPMDGangError(RuntimeError):
    pass


class MPMDTrainer:
    """Drive an MPMD pipeline to `total_steps`, elastically.

    `batch_fn(step) -> np.ndarray [B, S+1]` supplies the global token batch
    for a step (deterministic in `step` for exact resume trajectories); B
    must divide by dp_max * num_microbatches, and reshapes only ever pick
    dp values that DIVIDE dp_max (`_pick_dp`), so every reachable width
    shards it evenly.
    """

    def __init__(
        self,
        cfg,
        options: MPMDOptions,
        total_steps: int,
        batch_fn: Callable[[int], np.ndarray],
        run_config: Optional[RunConfig] = None,
        experiment_name: str = "mpmd",
    ):
        from ...models import gpt
        from .schedule import build_interleaved_1f1b

        gpt.check_mpmd_partitionable(
            cfg, options.num_stages, options.num_chunks
        )
        # Validates (S, M, v) — interleaving needs M % S == 0 — before any
        # actor spawns.
        build_interleaved_1f1b(
            0, options.num_stages, options.num_microbatches,
            options.num_chunks,
        )
        lo, hi = options.band()
        if not lo <= options.dp <= hi or hi % options.dp != 0:
            # Same contract _pick_dp enforces for reshaped widths: the
            # batch is sized for dp_max * M, so the INITIAL dp must divide
            # dp_max too — failing here beats spawning S*dp actors into a
            # guaranteed first-step ValueError.
            raise ValueError(
                f"dp={options.dp} must lie in [{lo}, {hi}] and divide "
                f"dp_max={hi} (batch divisibility contract)"
            )
        self.cfg = cfg
        self.opts = options
        self.total_steps = total_steps
        self.batch_fn = batch_fn
        self.run_config = run_config or RunConfig()
        self.experiment_name = experiment_name
        self.root = None  # resolved at fit()
        self.gang: Optional[_MPMDGang] = None
        self.dp = options.dp
        self._supervisor = None

    # ------------------------------------------------------------- spawn
    def _spawn(self, dp: int, restore_step: Optional[int]):
        """Create the S x dp gang, its edge channels, and the per-stage dp
        groups; run setup (join + restore) on every replica. Returns
        (gang, start_step)."""
        import cloudpickle

        from ...core import api
        from ...core.runtime_context import get_runtime_context
        from ...dag.compiled import make_edge_channel
        from ... import collective
        from ..elastic.ckpt import stage_root as stage_root_of

        import jax

        from ...models import gpt

        o, S, v = self.opts, self.opts.num_stages, self.opts.num_chunks
        gen = uuid.uuid4().hex[:8]
        remote_cls = api.remote(_StageReplica)
        # The full parameter tree is materialized ONCE, here on the driver,
        # and each replica receives only ITS stage's chunk slices — S*dp
        # gang actors must never each allocate the whole model just to
        # throw most of it away (at GPT-J scale that transient would OOM
        # exactly the hosts the ZeRO sharding is sized for).
        params_np = jax.tree_util.tree_map(
            np.asarray, gpt.init_params(jax.random.PRNGKey(o.seed), self.cfg)
        )
        stage_slices = [
            [
                gpt.extract_stage_params(
                    params_np, self.cfg, s, S, num_chunks=v, chunk=c
                )
                for c in range(v)
            ]
            for s in range(S)
        ]
        del params_np
        actors: Dict[tuple, Any] = {}
        for s in range(S):
            for r in range(dp):
                payload = cloudpickle.dumps(dict(
                    cfg=self.cfg, stage=s, num_stages=S, dp=dp, dp_rank=r,
                    stage_params=(
                        stage_slices[s] if v > 1 else stage_slices[s][0]
                    ),
                    num_microbatches=o.num_microbatches,
                    num_chunks=v, wire_dtype=o.wire_dtype,
                    send_depth=o.send_depth, zero=o.zero,
                    lr=o.lr, betas=o.betas, eps=o.eps,
                    weight_decay=o.weight_decay, seed=o.seed,
                    group_name=f"mpmd-{self.experiment_name}-{gen}-s{s}",
                    stage_root=stage_root_of(self.root, s), gen=gen,
                    inline_max_bytes=o.inline_max_bytes,
                    channel_timeout_s=o.channel_timeout_s,
                ))
                actors[(s, r)] = remote_cls.options(
                    num_cpus=o.num_cpus_per_replica
                ).remote(payload)
        groups = [
            f"mpmd-{self.experiment_name}-{gen}-s{s}" for s in range(S)
        ] if dp > 1 else []
        for s in range(S):
            if dp > 1:
                collective.create_collective_group(
                    [actors[(s, r)] for r in range(dp)], dp, list(range(dp)),
                    group_name=groups[s],
                )

        # Edge channels: replica r of stage s -> replica r of stage s+1
        # (fwd) and back (bwd) PER CHUNK, plus the wrap edges chunk c of
        # stage S-1 -> chunk c+1 of stage 0 when interleaved, plus the
        # tied-embedding bridge pair between the boundary stages — all
        # built with the compiled-DAG channel chooser so same-node edges
        # ride shm and cross-node edges ride TCP.
        driver_node = get_runtime_context().get_node_id()
        nodes = {
            key: nid for key, nid in zip(
                actors, api.get([a.node_id.remote() for a in actors.values()])
            )
        }
        channels = []
        edges: Dict[tuple, Dict[str, Any]] = {
            key: {
                "fwd_in": [None] * v, "fwd_out": [None] * v,
                "bwd_in": [None] * v, "bwd_out": [None] * v,
            }
            for key in actors
        }

        def connect(src, dst, kind, src_c, dst_c):
            ch = make_edge_channel(
                o.channel_buffer_bytes, nodes[src], [nodes[dst]], 1,
                actors[src], driver_node,
            )
            channels.append(ch)
            edges[src][f"{kind}_out"][src_c] = ch
            edges[dst][f"{kind}_in"][dst_c] = ch.with_reader_slot(0)

        for r in range(dp):
            for c in range(v):
                for s in range(S - 1):
                    connect((s, r), (s + 1, r), "fwd", c, c)
                    connect((s + 1, r), (s, r), "bwd", c, c)
            # Wrap: virtual stage c*S + (S-1) feeds (c+1)*S + 0 — the
            # forward leaves stage S-1's chunk c into stage 0's chunk c+1
            # (and the grad comes back).
            for c in range(v - 1):
                connect((S - 1, r), (0, r), "fwd", c, c + 1)
                connect((0, r), (S - 1, r), "bwd", c + 1, c)
            if self.cfg.tie_embeddings and S > 1:
                b_fwd = make_edge_channel(
                    o.channel_buffer_bytes, nodes[(0, r)],
                    [nodes[(S - 1, r)]], 1, actors[(0, r)], driver_node,
                )
                b_bwd = make_edge_channel(
                    o.channel_buffer_bytes, nodes[(S - 1, r)],
                    [nodes[(0, r)]], 1, actors[(S - 1, r)], driver_node,
                )
                channels.extend([b_fwd, b_bwd])
                edges[(0, r)]["bridge_out"] = b_fwd
                edges[(S - 1, r)]["bridge_in"] = b_fwd.with_reader_slot(0)
                edges[(S - 1, r)]["bridge_out"] = b_bwd
                edges[(0, r)]["bridge_in"] = b_bwd.with_reader_slot(0)

        gang = _MPMDGang(actors, channels, groups)
        try:
            steps = api.get(
                [
                    actors[key].setup.remote(
                        cloudpickle.dumps(edges[key]), restore_step
                    )
                    for key in actors
                ],
                timeout=o.step_timeout_s * 2 + 120,
            )
        except Exception as e:  # noqa: BLE001 — a member died mid-setup
            gang.shutdown()
            raise MPMDGangError(f"gang setup failed: {e!r}") from e
        start = max(steps)
        if len(set(steps)) > 1:
            gang.shutdown()
            raise MPMDGangError(
                f"stage replicas restored inconsistent steps {steps}"
            )
        return gang, start

    # --------------------------------------------------------------- fit
    def fit(self) -> Dict[str, Any]:
        from ...core import api  # noqa: F401 — runtime must be initialized
        from ..elastic import GangSupervisor

        o, S = self.opts, self.opts.num_stages
        self.root = self.run_config.resolve_storage()
        lo, hi = o.band()
        supervisor = GangSupervisor(
            ScalingConfig(
                num_workers=S * self.dp,
                min_workers=S * lo,
                max_workers=S * hi,
                resources_per_worker={"CPU": o.num_cpus_per_replica},
            ),
            self.run_config.failure_config or FailureConfig(),
            experiment_name=self.experiment_name,
        )
        self._supervisor = supervisor
        history: List[Dict[str, Any]] = []
        recovery_t0: Optional[float] = None
        try:
            return self._fit_loop(supervisor, history, recovery_t0, lo, hi)
        except BaseException:
            # A non-gang exception (config error, KeyboardInterrupt) must
            # not leak a live S x dp gang + watch thread behind the raise.
            supervisor.stop_watch()
            if self.gang is not None:
                self.gang.shutdown()
                self.gang = None
            raise

    def _fit_loop(self, supervisor, history, recovery_t0, lo, hi):
        from ..elastic import latest_common_committed

        S = self.opts.num_stages
        while True:
            try:
                found = latest_common_committed(self.root, S)
                restore_step = found[0] if found else None
                self.gang, start = self._spawn(self.dp, restore_step)
                # The supervisor owns group aborts on failure: every
                # stage's dp rendezvous is interrupted inside abort_mesh
                # (its _collective_group accepts the list), so survivors
                # never wait out a round on a dead peer.
                supervisor.watch(
                    self.gang, collective_group=list(self.gang.groups)
                )
                if recovery_t0 is not None:
                    supervisor.record_recovery(time.monotonic() - recovery_t0)
                    recovery_t0 = None
                self._run_steps(start, history, supervisor)
                self._finish()
                supervisor.stop_watch()
                return {
                    "history": history,
                    "error": None,
                    "attempts": supervisor.attempts,
                    "dp": self.dp,
                }
            except MPMDGangError as e:
                if recovery_t0 is None:
                    recovery_t0 = time.monotonic()
                supervisor.abort_mesh(self.gang)
                self.gang = None
                decision = supervisor.on_failure(str(e))
                if decision.stop:
                    logger.error(
                        "MPMD gang failed permanently after %d attempt(s): %s",
                        supervisor.attempts, e,
                    )
                    return {
                        "history": history,
                        "error": str(e),
                        "attempts": supervisor.attempts,
                        "dp": self.dp,
                    }
                logger.warning(
                    "MPMD gang failure (%s) — restart %d after %.1fs",
                    e, supervisor.attempts, decision.backoff_s,
                )
                if decision.backoff_s > 0:
                    time.sleep(decision.backoff_s)
                # RESHAPE: re-pick dp from what the cluster can place NOW
                # (measured after the backoff so the dead gang's resources
                # have drained), clamped to the configured band AND to
                # divisors of dp_max — the batch contract is divisibility
                # by dp_max * M, which only guarantees divisibility for dp
                # that divide dp_max (dp=3 in a [1,4] band would crash the
                # step loop on a batch sized for 4).
                world = supervisor.plan_world_size()
                new_dp = self._pick_dp(
                    world // S if world else self.dp, lo, hi
                )
                if new_dp != self.dp:
                    logger.warning(
                        "MPMD pipeline reshapes: dp %d -> %d", self.dp, new_dp
                    )
                    self.dp = new_dp

    @staticmethod
    def _pick_dp(feasible: int, lo: int, hi: int) -> int:
        """Largest dp in [lo, hi] that fits `feasible` AND divides the band
        ceiling (see reshape comment). The candidate set is never empty (hi
        divides itself); when even the smallest candidate exceeds feasible
        it is returned anyway — the spawn then fails and consumes restart
        budget honestly rather than deadlocking the policy loop."""
        candidates = [d for d in range(lo, hi + 1) if hi % d == 0]
        fitting = [d for d in candidates if d <= feasible]
        return max(fitting) if fitting else min(candidates)

    def _run_steps(self, start: int, history, supervisor):
        from ...core import api

        o, S, dp = self.opts, self.opts.num_stages, self.dp
        for step in range(start, self.total_steps):
            reason = supervisor.failure()
            if reason:
                raise MPMDGangError(f"gang member died ({reason})")
            batch = np.asarray(self.batch_fn(step))
            if batch.shape[0] % (dp * o.num_microbatches) != 0:
                raise ValueError(
                    f"batch {batch.shape[0]} not divisible by dp*microbatches "
                    f"({dp}*{o.num_microbatches})"
                )
            slices = np.array_split(batch, dp)
            save = (step + 1) % max(1, o.ckpt_every) == 0
            refs, keys = [], []
            for (s, r), actor in self.gang.actors.items():
                tokens = slices[r] if (s == 0 or s == S - 1) else None
                refs.append(actor.run_step.remote(tokens, save))
                keys.append((s, r))
            t0 = time.monotonic()
            out = self._get_step_results(refs, step, supervisor)
            wall = time.monotonic() - t0
            metrics = dict(zip(keys, out))
            last = [metrics[(S - 1, r)] for r in range(dp)]
            per_stage0 = [metrics[(s, 0)] for s in range(S)]
            # Busy = stage compute + optimizer update — the same numerator
            # the local harness and flight.pipeline_report use, so all
            # three bubble sources stay directly comparable.
            busy = sum(
                m["busy_s"] + m.get("update_s", 0.0)
                for m in metrics.values()
            )
            bubble = max(0.0, 1.0 - busy / (wall * S * dp))
            history.append({
                "step": step + 1,
                "loss": float(np.mean([m["loss"] for m in last])),
                "grad_norm": float(
                    np.sqrt(sum(m["grad_sumsq"] for m in per_stage0))
                ),
                "wall_s": wall,
                "bubble_frac": bubble,
                "opt_bytes_per_replica": max(
                    m["opt_bytes"] for m in metrics.values()
                ),
                "dp": dp,
            })
            try:
                # The trainer's wall-clock aggregate; the span-derived
                # attribution (flight.pipeline_report, source="flight")
                # cross-checks it from the stage actors' slot spans.
                from ...util.metrics import train_metrics

                train_metrics()["train_pipeline_bubble_fraction"].set(
                    bubble, tags={"source": "trainer"})
            except Exception:  # noqa: BLE001
                pass

    def _get_step_results(self, refs, step: int, supervisor):
        """Collect one step's replica results in SHORT slices, consulting
        the supervisor between them: a member death detected through the
        controller feed aborts the step within the poll window instead of
        waiting out the full step deadline on RPCs that will never
        complete."""
        from ...core import api
        from ...core.exceptions import GetTimeoutError

        deadline = time.monotonic() + self.opts.step_timeout_s
        while True:
            reason = supervisor.failure()
            if reason:
                raise MPMDGangError(f"gang member died ({reason})")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise MPMDGangError(
                    f"step {step} timed out after "
                    f"{self.opts.step_timeout_s:.0f}s"
                )
            try:
                return api.get(refs, timeout=min(2.0, remaining))
            except GetTimeoutError:
                continue
            except Exception as e:  # noqa: BLE001 — a member died
                raise MPMDGangError(f"step {step} failed: {e!r}") from e

    def _finish(self):
        from ...core import api

        try:
            api.get(
                [a.flush.remote() for a in self.gang.actors.values()],
                timeout=120,
            )
        finally:
            self.gang.shutdown()
            self.gang = None
