"""ray_tpu.train.mpmd — MPMD pipeline parallelism + ZeRO sharded updates.

The training-at-scale composition (ROADMAP item 2; arXiv 2412.14374 +
2004.13336): the model splits into S stages, each stage a SEPARATE jit
program on its own gang actor (not one SPMD program over a pp axis — that
in-mesh path stays in `ray_tpu.parallel.pipeline`), a host-side 1F1B
schedule drives microbatch activations/grads stage-to-stage over
compiled-DAG channels with large tensors riding arena segments + bulk span
pulls, and each stage's data-parallel replicas run the ZeRO-sharded weight
update (reduce-scatter grads, 1/dp optimizer-state shards, all-gather
params). Composed with `train.elastic`: a member death aborts the mesh via
the gang supervisor, dp is re-picked from feasible capacity, and stage-local
checkpoint shards restore across the reshape.

Entry points:
  * `MPMDTrainer` (trainer.py) — the cluster trainer (gang actors).
  * `run_local_pipeline` (local.py) — same runners on threads; parity
    harness and schedule gate.
  * `StageRunner`, `build_1f1b`/`build_interleaved_1f1b`, `ShardedAdamW`,
    `WireCodec` — the composable pieces (interleaved virtual stages cut
    the bubble toward (S-1)/(v*M+S-1); the bf16 wire halves hop bytes).

See docs/MPMD_TRAINING.md.
"""

from .schedule import (
    build_1f1b,
    build_interleaved_1f1b,
    max_in_flight,
    theoretical_bubble_fraction,
)
from .stage import StageRunner
from .transport import ActTransport, ChannelEdge, LocalEdge, WireCodec
from .zero import (
    LocalDpComm,
    ReplicatedAdamW,
    ShardedAdamW,
    SoloComm,
    StoreDpComm,
    make_local_comms,
)
from .local import run_local_pipeline
from .trainer import MPMDOptions, MPMDTrainer

__all__ = [
    "build_1f1b",
    "build_interleaved_1f1b",
    "WireCodec",
    "max_in_flight",
    "theoretical_bubble_fraction",
    "StageRunner",
    "ActTransport",
    "ChannelEdge",
    "LocalEdge",
    "ShardedAdamW",
    "ReplicatedAdamW",
    "SoloComm",
    "StoreDpComm",
    "LocalDpComm",
    "make_local_comms",
    "run_local_pipeline",
    "MPMDOptions",
    "MPMDTrainer",
]
