"""In-process MPMD pipeline: the REAL 1F1B interleaving on threads.

One thread per (stage, dp-replica), queue edges, in-process dp collectives.
This is the parity harness (losses/grads vs single-jit GPipe vs unpipelined
on one CPU mesh, no cluster boot) and the deadlock gate for the schedule —
the cluster trainer (`trainer.py`) swaps in gang actors, compiled-DAG
channels, and the object-store collectives around the SAME StageRunner.
Interleaving (num_chunks = v > 1) wires per-chunk edges plus the wrap
edges chunk c of stage S-1 -> chunk c+1 of stage 0; tied embeddings add
the first/last-stage bridge pair (always f32 — gradients for the update
never ride the lossy wire). `wire_dtype="bf16"` runs every activation/
grad hop through the real WireCodec so the loss-curve gate exercises the
actual cast/restore.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .stage import StageRunner
from .transport import LocalEdge, WireCodec
from .zero import make_local_comms


def run_local_pipeline(
    cfg,
    num_stages: int,
    dp: int,
    num_microbatches: int,
    batches: List[np.ndarray],
    *,
    params=None,
    seed: int = 0,
    num_chunks: int = 1,
    wire_dtype: str = "f32",
    zero: bool = True,
    lr: float = 1e-3,
    betas=(0.9, 0.95),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    step_timeout_s: float = 120.0,
    on_step: Optional[Callable[[int], None]] = None,
) -> Dict[str, Any]:
    """Train over `batches` (each [B, S+1] int tokens, B divisible by
    dp * num_microbatches) and return {"history": per-step driver metrics,
    "params": final full param tree (host), "runners": the stage runners,
    "wall_s"/"bubble_frac": run aggregates, "wire_stats": codec byte
    counters summed over every activation/grad edge}.
    """
    import jax

    from ...models import gpt

    S, v = num_stages, num_chunks
    gpt.check_mpmd_partitionable(cfg, S, v)
    if params is None:
        params = gpt.init_params(jax.random.PRNGKey(seed), cfg)
    params_np = jax.tree_util.tree_map(np.asarray, params)

    runners: List[List[StageRunner]] = []
    for s in range(S):
        comms = make_local_comms(dp)
        chunk_trees = [
            gpt.extract_stage_params(
                params_np, cfg, s, S, num_chunks=v, chunk=c
            )
            for c in range(v)
        ]
        runners.append([
            StageRunner(
                cfg, s, S, num_microbatches,
                chunk_trees if v > 1 else chunk_trees[0],
                comms[r], replica=r, num_chunks=v, zero=zero, lr=lr,
                betas=betas, eps=eps, weight_decay=weight_decay,
            )
            for r in range(dp)
        ])

    # The activation/grad wire: one codec (and its byte counters) shared by
    # every edge; bridges get their own f32 identity codec.
    codec = WireCodec(wire_dtype)
    bridge = cfg.tie_embeddings and S > 1
    for r in range(dp):
        fwd_in = [[None] * v for _ in range(S)]
        fwd_out = [[None] * v for _ in range(S)]
        bwd_in = [[None] * v for _ in range(S)]
        bwd_out = [[None] * v for _ in range(S)]

        def edge():
            return LocalEdge(timeout_s=step_timeout_s, codec=codec)

        for c in range(v):
            for s in range(S - 1):
                e, eb = edge(), edge()
                fwd_out[s][c] = e
                fwd_in[s + 1][c] = e
                bwd_out[s + 1][c] = eb
                bwd_in[s][c] = eb
        # Wrap edges: virtual stage c*S + (S-1) feeds (c+1)*S + 0.
        for c in range(v - 1):
            e, eb = edge(), edge()
            fwd_out[S - 1][c] = e
            fwd_in[0][c + 1] = e
            bwd_out[0][c + 1] = eb
            bwd_in[S - 1][c] = eb
        bridges = {}
        if bridge:
            b_fwd = LocalEdge(timeout_s=step_timeout_s)
            b_bwd = LocalEdge(timeout_s=step_timeout_s)
            bridges[0] = {"bridge_out": b_fwd, "bridge_in": b_bwd}
            bridges[S - 1] = {"bridge_out": b_bwd, "bridge_in": b_fwd}
        for s in range(S):
            runners[s][r].bind_edges(
                fwd_in=fwd_in[s], fwd_out=fwd_out[s],
                bwd_in=bwd_in[s], bwd_out=bwd_out[s],
                **bridges.get(s, {}),
            )

    results: Dict[tuple, List[Dict[str, Any]]] = {}
    errors: List[BaseException] = []

    def worker(s: int, r: int):
        try:
            out = []
            for step, batch in enumerate(batches):
                sl = None
                if s == 0 or s == S - 1:
                    sl = np.array_split(np.asarray(batch), dp)[r]
                out.append(runners[s][r].run_step(sl))
                if on_step is not None and s == 0 and r == 0:
                    on_step(step)
            results[(s, r)] = out
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(s, r), daemon=True)
        for s in range(S) for r in range(dp)
    ]
    import time as _time

    run_t0 = _time.monotonic()
    for t in threads:
        t.start()
    deadline = step_timeout_s * max(1, len(batches))
    for t in threads:
        t.join(timeout=deadline)
        if t.is_alive():
            raise RuntimeError(
                "local MPMD pipeline wedged (schedule deadlock or a dead "
                f"sibling thread); errors so far: {errors!r}"
            )
    run_wall = _time.monotonic() - run_t0
    if errors:
        raise errors[0]

    history: List[Dict[str, Any]] = []
    for step in range(len(batches)):
        last = [results[(S - 1, r)][step] for r in range(dp)]
        per_stage = [results[(s, 0)][step] for s in range(S)]
        history.append({
            "step": step + 1,
            "loss": float(np.mean([m["loss"] for m in last])),
            "grad_norm": float(
                np.sqrt(sum(m["grad_sumsq"] for m in per_stage))
            ),
            "busy_s": sum(
                results[(s, r)][step]["busy_s"]
                for s in range(S) for r in range(dp)
            ),
            "opt_bytes_per_replica": max(
                m["opt_bytes"] for m in per_stage
            ),
        })

    # Reassemble the full model tree from replica-0 runners in VIRTUAL
    # STAGE order — layer slices concatenate chunk-major (vs = c*S + s),
    # which is exactly how extract_stage_params dealt them out. Replicas
    # are identical post-update by the all-gather contract; with tied
    # embeddings, tok_embed appears on both boundary virtual stages
    # (bit-identical post-bridge) and setdefault keeps the first.
    merged: Dict[str, np.ndarray] = {}
    layer_parts: Dict[str, List[np.ndarray]] = {}
    for c in range(v):
        for s in range(S):
            tree = runners[s][0].chunk_params_host(c)
            for k, val in tree.items():
                if k in gpt_layer_keys():
                    layer_parts.setdefault(k, []).append(np.asarray(val))
                else:
                    merged.setdefault(k, np.asarray(val))
    for k, parts in layer_parts.items():
        merged[k] = np.concatenate(parts, axis=0)
    # Aggregate pipeline-bubble number for the whole run, trainer-style
    # denominator (wall * lanes — PHYSICAL lanes S*dp, not S*v*dp: a
    # stage's chunks share one host thread, so its capacity is one lane)
    # but with the optimizer update included in the numerator — the same
    # busy definition as the flight recorder's span-derived attribution
    # (flight.pipeline_report), so the two are directly cross-checkable
    # on this harness.
    busy_total = sum(
        m["busy_s"] + m.get("update_s", 0.0)
        for outs in results.values() for m in outs
    )
    lanes = S * dp
    bubble = max(0.0, 1.0 - busy_total / max(run_wall * lanes, 1e-9))
    return {"history": history, "params": merged, "runners": runners,
            "wall_s": run_wall, "bubble_frac": bubble,
            "wire_stats": dict(codec.stats)}


def gpt_layer_keys():
    from ...models.gpt import _LAYER_KEYS

    return _LAYER_KEYS
