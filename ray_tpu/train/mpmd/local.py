"""In-process MPMD pipeline: the REAL 1F1B interleaving on threads.

One thread per (stage, dp-replica), queue edges, in-process dp collectives.
This is the parity harness (losses/grads vs single-jit GPipe vs unpipelined
on one CPU mesh, no cluster boot) and the deadlock gate for the schedule —
the cluster trainer (`trainer.py`) swaps in gang actors, compiled-DAG
channels, and the object-store collectives around the SAME StageRunner.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .stage import StageRunner
from .transport import LocalEdge
from .zero import make_local_comms


def run_local_pipeline(
    cfg,
    num_stages: int,
    dp: int,
    num_microbatches: int,
    batches: List[np.ndarray],
    *,
    params=None,
    seed: int = 0,
    zero: bool = True,
    lr: float = 1e-3,
    betas=(0.9, 0.95),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    step_timeout_s: float = 120.0,
    on_step: Optional[Callable[[int], None]] = None,
) -> Dict[str, Any]:
    """Train over `batches` (each [B, S+1] int tokens, B divisible by
    dp * num_microbatches) and return {"history": per-step driver metrics,
    "params": final full param tree (host), "runners": the stage runners}.
    """
    import jax

    from ...models import gpt

    gpt.check_mpmd_partitionable(cfg, num_stages)
    if params is None:
        params = gpt.init_params(jax.random.PRNGKey(seed), cfg)
    params_np = jax.tree_util.tree_map(np.asarray, params)

    runners: List[List[StageRunner]] = []
    for s in range(num_stages):
        comms = make_local_comms(dp)
        stage_params = gpt.extract_stage_params(params_np, cfg, s, num_stages)
        runners.append([
            StageRunner(
                cfg, s, num_stages, num_microbatches, stage_params,
                comms[r], replica=r, zero=zero, lr=lr, betas=betas, eps=eps,
                weight_decay=weight_decay,
            )
            for r in range(dp)
        ])
    for s in range(num_stages - 1):
        for r in range(dp):
            fwd = LocalEdge(timeout_s=step_timeout_s)
            bwd = LocalEdge(timeout_s=step_timeout_s)
            runners[s][r].bind_edges(
                fwd_in=runners[s][r].fwd_in, fwd_out=fwd,
                bwd_in=bwd, bwd_out=runners[s][r].bwd_out,
            )
            runners[s + 1][r].bind_edges(
                fwd_in=fwd, fwd_out=runners[s + 1][r].fwd_out,
                bwd_in=runners[s + 1][r].bwd_in, bwd_out=bwd,
            )

    results: Dict[tuple, List[Dict[str, Any]]] = {}
    errors: List[BaseException] = []

    def worker(s: int, r: int):
        try:
            out = []
            for step, batch in enumerate(batches):
                sl = None
                if s == 0 or s == num_stages - 1:
                    sl = np.array_split(np.asarray(batch), dp)[r]
                out.append(runners[s][r].run_step(sl))
                if on_step is not None and s == 0 and r == 0:
                    on_step(step)
            results[(s, r)] = out
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(s, r), daemon=True)
        for s in range(num_stages) for r in range(dp)
    ]
    import time as _time

    run_t0 = _time.monotonic()
    for t in threads:
        t.start()
    deadline = step_timeout_s * max(1, len(batches))
    for t in threads:
        t.join(timeout=deadline)
        if t.is_alive():
            raise RuntimeError(
                "local MPMD pipeline wedged (schedule deadlock or a dead "
                f"sibling thread); errors so far: {errors!r}"
            )
    run_wall = _time.monotonic() - run_t0
    if errors:
        raise errors[0]

    history: List[Dict[str, Any]] = []
    for step in range(len(batches)):
        last = [results[(num_stages - 1, r)][step] for r in range(dp)]
        per_stage = [results[(s, 0)][step] for s in range(num_stages)]
        history.append({
            "step": step + 1,
            "loss": float(np.mean([m["loss"] for m in last])),
            "grad_norm": float(
                np.sqrt(sum(m["grad_sumsq"] for m in per_stage))
            ),
            "busy_s": sum(
                results[(s, r)][step]["busy_s"]
                for s in range(num_stages) for r in range(dp)
            ),
            "opt_bytes_per_replica": max(
                m["opt_bytes"] for m in per_stage
            ),
        })

    # Reassemble the full model tree from stage 0/last replicas (replicas
    # are identical post-update by the all-gather contract).
    merged: Dict[str, np.ndarray] = {}
    layer_parts: Dict[str, List[np.ndarray]] = {}
    for s in range(num_stages):
        tree = runners[s][0].params_host()
        for k, v in tree.items():
            if k in gpt_layer_keys():
                layer_parts.setdefault(k, []).append(np.asarray(v))
            else:
                merged.setdefault(k, np.asarray(v))
    for k, parts in layer_parts.items():
        merged[k] = np.concatenate(parts, axis=0)
    # Aggregate pipeline-bubble number for the whole run, trainer-style
    # denominator (wall * lanes) but with the optimizer update included in
    # the numerator — the same busy definition as the flight recorder's
    # span-derived attribution (flight.pipeline_report), so the two are
    # directly cross-checkable on this harness.
    busy_total = sum(
        m["busy_s"] + m.get("update_s", 0.0)
        for outs in results.values() for m in outs
    )
    lanes = num_stages * dp
    bubble = max(0.0, 1.0 - busy_total / max(run_wall * lanes, 1e-9))
    return {"history": history, "params": merged, "runners": runners,
            "wall_s": run_wall, "bubble_frac": bubble}


def gpt_layer_keys():
    from ...models.gpt import _LAYER_KEYS

    return _LAYER_KEYS
