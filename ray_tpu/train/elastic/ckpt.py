"""Async sharded checkpointing with group-commit atomicity.

Design (ISSUE 4 tentpole, plane 2; reference: TorchTitan's async DCP saves,
arXiv 2410.06511 §3):

- Each rank snapshots its shard to host memory synchronously (a cheap
  numpy copy) and hands it to a background writer thread — the training
  step never blocks on the filesystem. `ckpt_save_overlap_seconds`
  (util/metrics.py) records how much write time was hidden behind compute.
- Layout: `<root>/step_{step:08d}.{gen}/shard_{rank:05d}.pkl`, each shard
  written tmp → fsync → atomic rename. `gen` is the gang-incarnation token
  (one per WorkerGroup start): a shard written by a PREVIOUS incarnation
  can never be mixed with this one's into a frankenstein checkpoint —
  after a crash-and-restart the same step re-saves into a fresh directory.
- Group commit: after landing its own shard, every writer checks whether
  all `world_size` shards are present; the first to observe a full set
  writes the `COMMITTED` marker (tmp → fsync → rename → dir fsync). A
  checkpoint without the marker does not exist as far as restore is
  concerned, so a SIGKILL anywhere mid-save leaves the previous committed
  checkpoint restorable (atomicity acceptance test).
- Restore reshards: mode="sharded" shards are axis-0 partitions (rank
  order); a re-formed gang with a different world size concatenates and
  re-splits. mode="replicated" loads shard 0 for every rank.

Shard payloads are pickled host pytrees — `{"tree": ..., "state": ...}`
where state is the ElasticState payload (state.py).
"""

from __future__ import annotations

import os
import pickle
import queue
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .state import ElasticState

from ..checkpoint import _fsync_dir, _to_host

COMMIT_MARKER = "COMMITTED"
_STEP_DIR_RE = re.compile(r"^step_(\d{8})\.(.+)$")


def step_dir_name(step: int, gen: str) -> str:
    return f"step_{step:08d}.{gen}"


def stage_root(root: str, stage: int) -> str:
    """Per-stage checkpoint directory of an MPMD pipeline run: each stage's
    dp replicas write their shards (rank = dp index, world = dp) under
    `<root>/stage_NN/`, so the existing axis-0 reshard machinery applies
    per stage when the pipeline reshapes to a different dp width."""
    return os.path.join(root, f"stage_{stage:02d}")


def latest_common_committed(root: str, num_stages: int):
    """Newest step committed in EVERY stage directory — the only step the
    whole pipeline can restore coherently. Per-stage group commits are
    independent (a crash can land between stage commits), so the restore
    point is the intersection of committed steps, not any one stage's
    latest. Returns (step, [stage dirs]) or None."""
    per_stage = []
    for s in range(num_stages):
        sroot = stage_root(root, s)
        committed = {
            step: path
            for step, path in ShardedCheckpoint.list_checkpoints(sroot)
            if os.path.exists(os.path.join(path, COMMIT_MARKER))
        }
        if not committed:
            return None
        per_stage.append(committed)
    common = set(per_stage[0])
    for committed in per_stage[1:]:
        common &= set(committed)
    if not common:
        return None
    step = max(common)
    return step, [per_stage[s][step] for s in range(num_stages)]


def _write_atomic(path: str, data: bytes, tmp: Optional[str] = None) -> None:
    """Write-fsync-rename. `tmp` must be unique per WRITER when several
    processes race to produce the same `path` (the group-commit marker):
    with a shared tmp name the loser's rename throws FileNotFoundError
    after the winner renames the file away."""
    tmp = tmp or (path + ".tmp")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def _tree_map(fn, tree):
    from jax import tree_util

    return tree_util.tree_map(fn, tree)


def _lens_sidecar_name(rank: int) -> str:
    return f"shard_{rank:05d}.lens.json"


def _leaf_lens(leaves) -> List[Optional[int]]:
    """Per-leaf axis-0 length, None for replicated (non-array / 0-d)."""
    import numpy as np

    return [
        leaf.shape[0]
        if isinstance(leaf, np.ndarray) and leaf.ndim > 0 else None
        for leaf in leaves
    ]


def _read_lens_sidecar(
    ckpt_dir: str, rank: int, nleaves: int
) -> Optional[List[Optional[int]]]:
    """Advisory fast path for reshard restore: the writer's lens sidecar,
    or None (missing/corrupt/wrong leaf count — caller unpickles the full
    shard instead)."""
    import json

    try:
        with open(os.path.join(ckpt_dir, _lens_sidecar_name(rank))) as f:
            lens = json.load(f)
    except (OSError, ValueError):
        return None
    if (
        not isinstance(lens, list)
        or len(lens) != nleaves
        or not all(v is None or isinstance(v, int) for v in lens)
    ):
        return None
    return lens


def _snapshot(tree) -> Any:
    """Host copy of every leaf — the caller may donate/mutate its arrays the
    moment save() returns, so the writer must own the bytes."""
    import numpy as np

    def copy(x):
        h = _to_host(x)
        return np.array(h, copy=True) if isinstance(h, np.ndarray) else h

    return _tree_map(copy, tree)


class ShardedCheckpoint:
    """Static helpers over one checkpoint root directory."""

    @staticmethod
    def list_checkpoints(root: str) -> List[Tuple[int, str]]:
        """All checkpoint dirs (committed or not) as (step, path), ascending
        by (step, mtime)."""
        out = []
        try:
            names = os.listdir(root)
        except OSError:
            return []
        for name in names:
            m = _STEP_DIR_RE.match(name)
            if not m:
                continue
            path = os.path.join(root, name)
            if os.path.isdir(path):
                try:
                    mtime = os.stat(path).st_mtime
                except OSError:
                    mtime = 0.0
                out.append((int(m.group(1)), path, mtime))
        out.sort(key=lambda e: (e[0], e[2]))
        return [(step, path) for step, path, _ in out]

    @staticmethod
    def latest_committed(root: str) -> Optional[Tuple[int, str]]:
        """(step, dir) of the newest checkpoint bearing the COMMITTED
        marker; uncommitted (marker-less) dirs — crashed mid-save — are
        skipped."""
        for step, path in reversed(ShardedCheckpoint.list_checkpoints(root)):
            if os.path.exists(os.path.join(path, COMMIT_MARKER)):
                return step, path
        return None

    @staticmethod
    def read_meta(ckpt_dir: str) -> Dict[str, Any]:
        import json

        with open(os.path.join(ckpt_dir, COMMIT_MARKER)) as f:
            return json.load(f)

    @staticmethod
    def load_shard(ckpt_dir: str, rank: int) -> Dict[str, Any]:
        with open(os.path.join(ckpt_dir, f"shard_{rank:05d}.pkl"), "rb") as f:
            return pickle.load(f)

    @staticmethod
    def restore(
        root: str, rank: int, world_size: int, step: Optional[int] = None
    ) -> Optional[Tuple[ElasticState, Any]]:
        """Load the latest committed checkpoint for `rank` of a gang of
        `world_size`, resharding if the checkpoint was written by a gang of
        a different size. With `step`, pin to that exact committed step
        (the MPMD restore path: every stage must load the pipeline's COMMON
        committed step, not its own latest). Returns (state, tree) or None
        when no matching committed checkpoint exists."""
        if step is None:
            found = ShardedCheckpoint.latest_committed(root)
        else:
            found = next(
                (
                    (st, path)
                    for st, path in reversed(
                        ShardedCheckpoint.list_checkpoints(root)
                    )
                    if st == step
                    and os.path.exists(os.path.join(path, COMMIT_MARKER))
                ),
                None,
            )
        if found is None:
            return None
        _, ckpt_dir = found
        meta = ShardedCheckpoint.read_meta(ckpt_dir)
        saved_world = int(meta["world_size"])
        mode = meta.get("mode", "sharded")
        if mode == "replicated":
            payload = ShardedCheckpoint.load_shard(ckpt_dir, 0)
            return ElasticState.from_payload(payload["state"]), payload["tree"]
        if saved_world == world_size:
            payload = ShardedCheckpoint.load_shard(ckpt_dir, rank)
            return ElasticState.from_payload(payload["state"]), payload["tree"]
        # Reshard: each leaf is the axis-0 concatenation across the saved
        # ranks, re-split np.array_split-style into the new world size.
        # Non-array / 0-d leaves are treated as replicated (shard 0 wins).
        # Shards are loaded ONE AT A TIME (never the whole model at once —
        # that is the memory profile sharding exists to avoid): pass 1
        # records per-leaf axis-0 lengths — from the tiny lens sidecars the
        # writers left next to each shard when available (unpickling every
        # full shard just to read shapes would put O(world x model) of
        # deserialize on the recovery path), falling back to the shard
        # payload itself for sidecar-less dirs — pass 2 re-reads only the
        # shards overlapping this rank's slice and keeps just the overlap.
        import numpy as np
        from jax import tree_util

        payload0 = ShardedCheckpoint.load_shard(ckpt_dir, 0)
        leaves0, treedef = tree_util.tree_flatten(payload0["tree"])
        state0 = payload0["state"]
        rep_leaves = []  # replicated (non-array / 0-d) leaves from shard 0
        leaf_meta = []  # (trailing shape, dtype) per leaf, for empty slices
        for leaf in leaves0:
            sharded = isinstance(leaf, np.ndarray) and leaf.ndim > 0
            rep_leaves.append(None if sharded else leaf)
            leaf_meta.append(
                (leaf.shape[1:], leaf.dtype) if sharded else None
            )
        nleaves = len(leaves0)
        per_shard_lens = [_leaf_lens(leaves0)]  # shard -> lens per leaf
        for r in range(1, saved_world):
            lens = _read_lens_sidecar(ckpt_dir, r, nleaves)
            if lens is None:
                lens = _leaf_lens(tree_util.tree_flatten(
                    ShardedCheckpoint.load_shard(ckpt_dir, r)["tree"]
                )[0])
            per_shard_lens.append(lens)
        bounds = []  # this rank's [start, end) per leaf, None if replicated
        for i in range(nleaves):
            if per_shard_lens[0][i] is None:
                bounds.append(None)
                continue
            total = sum(per_shard_lens[r][i] for r in range(saved_world))
            q, rem = divmod(total, world_size)  # np.array_split sizing
            start = rank * q + min(rank, rem)
            bounds.append((start, start + q + (1 if rank < rem else 0)))

        pieces = [[] for _ in range(nleaves)]
        offsets = [0] * nleaves
        for r in range(saved_world):
            lens = per_shard_lens[r]
            need = any(
                b is not None and lens[i] is not None
                and offsets[i] < b[1] and offsets[i] + lens[i] > b[0]
                for i, b in enumerate(bounds)
            )
            leaves = (
                tree_util.tree_flatten(
                    ShardedCheckpoint.load_shard(ckpt_dir, r)["tree"]
                )[0]
                if need else None
            )
            for i, b in enumerate(bounds):
                if b is None or lens[i] is None:
                    continue
                if leaves is not None:
                    lo = max(b[0] - offsets[i], 0)
                    hi = min(b[1] - offsets[i], lens[i])
                    if lo < hi:
                        pieces[i].append(np.asarray(leaves[i])[lo:hi].copy())
                offsets[i] += lens[i]

        out_leaves = []
        for i in range(nleaves):
            if bounds[i] is None:
                out_leaves.append(rep_leaves[i])
            elif pieces[i]:
                out_leaves.append(
                    pieces[i][0] if len(pieces[i]) == 1
                    else np.concatenate(pieces[i], axis=0)
                )
            else:  # more new ranks than rows: this rank's slice is empty
                trail, dtype = leaf_meta[i]
                out_leaves.append(np.empty((0,) + trail, dtype=dtype))

        tree = tree_util.tree_unflatten(treedef, out_leaves)
        return ElasticState.from_payload(state0), tree


class AsyncShardWriter:
    """Per-rank background checkpoint writer.

    save() snapshots and enqueues (bounded queue — a writer that cannot
    keep up applies backpressure rather than buffering unbounded host
    copies); the writer thread lands the shard durably and attempts the
    group commit. flush() drains; close() drains and stops."""

    def __init__(
        self,
        root: str,
        rank: int,
        world_size: int,
        gen: str = "0",
        mode: str = "sharded",
        queue_depth: int = 2,
        commit_wait_s: float = 0.0,
        metric_tags: Optional[Dict[str, str]] = None,
        keep: Optional[int] = 3,
    ):
        if mode not in ("sharded", "replicated"):
            raise ValueError(f"unknown checkpoint mode {mode!r}")
        self.root = root
        self.rank = rank
        self.world_size = world_size
        self.gen = str(gen)
        self.mode = mode
        self.commit_wait_s = commit_wait_s
        self.metric_tags = dict(metric_tags or {})
        self.keep = keep
        os.makedirs(root, exist_ok=True)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, queue_depth))
        # Pending = enqueued-but-not-yet-landed saves. A plain "queue empty
        # + idle flag" protocol has a window (between dequeue and
        # flag-clear) where flush() could return with a shard mid-write;
        # the counter is decremented only AFTER the shard landed.
        self._pending = 0
        self._cv = threading.Condition()
        self._stop = False
        self._error: Optional[BaseException] = None
        self.saves = 0
        self.commits = 0
        self.last_block_s = 0.0  # time save() spent blocking the step
        self.last_write_s = 0.0  # write time hidden behind training
        self._thread = threading.Thread(
            target=self._run, name=f"elastic-ckpt-w{rank}", daemon=True
        )
        self._thread.start()

    # ---------------------------------------------------------------- API
    def save(self, step: int, tree: Any, state: ElasticState) -> None:
        """Snapshot + enqueue; returns as soon as the host copy is made."""
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error
        t0 = time.monotonic()
        snap = _snapshot(tree)
        payload = {"tree": snap, "state": state.to_payload()}
        with self._cv:
            self._pending += 1
        self._q.put((step, payload))  # blocks only when queue_depth exceeded
        self.last_block_s = time.monotonic() - t0
        self.saves += 1

    def flush(self, timeout: float = 60.0) -> bool:
        """Wait until every enqueued save has landed (and commit was
        attempted). Returns False on timeout; raises when the writer
        failed — a shard that never hit disk must not read as a successful
        flush (the failed save would otherwise only surface if another
        save() happened to follow)."""
        with self._cv:
            done = self._cv.wait_for(lambda: self._pending == 0, timeout)
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error
        return done

    def close(self, timeout: float = 60.0) -> None:
        """Drain and stop. Raises when the writer failed OR the drain timed
        out — queued shards abandoned by the shutdown must surface as a
        failure (worker_group treats a raising close() as worker error),
        never as a successful finish with silently-missing checkpoints."""
        try:
            if not self.flush(timeout):
                raise RuntimeError(
                    f"checkpoint writer drain timed out after {timeout}s; "
                    "queued shards were abandoned"
                )
        finally:
            self._shutdown_thread()

    def _shutdown_thread(self) -> None:
        self._stop = True
        try:
            self._q.put_nowait(None)  # wake a get()-blocked thread
        except queue.Full:
            pass  # thread is mid-item; it observes _stop on its next loop
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------- worker
    def _run(self):
        while True:
            item = self._q.get()
            if item is None or self._stop:
                # Account for every save we are abandoning (this item and
                # anything still queued) so a late flush() doesn't wait out
                # its full timeout on work that will never happen.
                with self._cv:
                    if item is not None:
                        self._pending -= 1
                    while True:
                        try:
                            dropped = self._q.get_nowait()
                        except queue.Empty:
                            break
                        if dropped is not None:
                            self._pending -= 1
                    self._cv.notify_all()
                return
            step, payload = item
            t0 = time.monotonic()
            try:
                self._write_shard(step, payload)
            except BaseException as e:  # noqa: BLE001 — surfaced on next save()
                self._error = e
            self.last_write_s = time.monotonic() - t0
            try:
                from ...util import metrics as _m

                _m.elastic_metrics()["ckpt_save_overlap_seconds"].observe(
                    self.last_write_s, tags=self.metric_tags
                )
            except Exception:  # noqa: BLE001 — metrics never load-bearing
                pass
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()

    def _write_shard(self, step: int, payload: Dict[str, Any]) -> None:
        import json

        from jax import tree_util

        ckpt_dir = os.path.join(self.root, step_dir_name(step, self.gen))
        os.makedirs(ckpt_dir, exist_ok=True)
        shard_path = os.path.join(ckpt_dir, f"shard_{self.rank:05d}.pkl")
        _write_atomic(shard_path, pickle.dumps(payload))
        # Lens sidecar: reshard restore's pass 1 reads per-leaf axis-0
        # lengths from this tiny JSON instead of unpickling the full shard.
        # Written AFTER the shard (a sidecar without its shard would be a
        # lie; the reverse just falls back to the slow path).
        _write_atomic(
            os.path.join(ckpt_dir, _lens_sidecar_name(self.rank)),
            json.dumps(
                _leaf_lens(tree_util.tree_flatten(payload["tree"])[0])
            ).encode(),
        )
        _fsync_dir(ckpt_dir)
        if self._try_commit(step, ckpt_dir):
            self._prune()

    def _prune(self) -> None:
        """Retention: keep the newest `keep` COMMITTED checkpoints and drop
        every dir (committed or not, any incarnation) strictly older than
        the oldest kept one — per-step saves would otherwise grow the disk
        without bound, and marker-less partials from dead incarnations
        would accumulate forever. Dirs newer than the threshold are left
        alone (an in-progress save must not be yanked mid-write). Every
        rank's writer prunes; the racing rmtrees are idempotent."""
        if self.keep is None:
            return
        import shutil

        committed = [
            (step, path)
            for step, path in ShardedCheckpoint.list_checkpoints(self.root)
            if os.path.exists(os.path.join(path, COMMIT_MARKER))
        ]
        if len(committed) <= self.keep:
            return
        threshold = committed[-self.keep][0]
        for step, path in ShardedCheckpoint.list_checkpoints(self.root):
            if step < threshold:
                shutil.rmtree(path, ignore_errors=True)

    def _try_commit(self, step: int, ckpt_dir: str) -> bool:
        """Write the group-commit marker iff every rank's shard has landed
        in THIS incarnation's directory. Every writer races to commit; the
        marker rename is atomic and idempotent, so double-commit is
        harmless. With commit_wait_s > 0 the writer lingers briefly for
        stragglers (useful when only one rank checkpoints frequently)."""
        marker = os.path.join(ckpt_dir, COMMIT_MARKER)
        deadline = time.monotonic() + self.commit_wait_s
        while True:
            if os.path.exists(marker):
                return True
            have = all(
                os.path.exists(os.path.join(ckpt_dir, f"shard_{r:05d}.pkl"))
                for r in range(self.world_size)
            )
            if have:
                import json

                meta = {
                    "step": step,
                    "world_size": self.world_size,
                    "mode": self.mode,
                    "gen": self.gen,
                    "ts": time.time(),
                }
                try:
                    _write_atomic(
                        marker, json.dumps(meta).encode(),
                        tmp=f"{marker}.tmp.{self.rank}",
                    )
                except OSError:
                    # Lost the commit race to another rank's writer — fine,
                    # the marker exists either way.
                    if not os.path.exists(marker):
                        raise
                _fsync_dir(ckpt_dir)
                self.commits += 1
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
