"""Deterministic-resume state that travels with every elastic checkpoint.

The contract (ISSUE 4 tentpole, plane 3): a run killed at step k and resumed
from the last committed checkpoint must produce the SAME loss trajectory as
an unkilled run. That holds iff everything the loop consumes besides the
model shard is restored too — the step counter and the data-iterator
offsets. Offsets are stored GLOBALLY (total samples consumed across the
gang), not per-rank, so a resume with a different world size (elasticity
band shrink) can re-derive each rank's local position: rank r of W workers
continues at global_offset + r, striding W.

Reference analog: TorchTitan (arXiv 2410.06511) checkpoints
(step, dataloader state) next to the DCP shards for exactly this reason.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass
class ElasticState:
    """Loop progress snapshot. `step` is the NEXT step to run (a checkpoint
    written after finishing step s carries step=s+1)."""

    step: int = 0
    # dataset name -> global sample offset (sum over ranks). World-size
    # independent by construction — see module docstring.
    data_offsets: Dict[str, int] = field(default_factory=dict)
    # Free-form user extras (rng seeds, schedule phase, ...). Must be
    # JSON-serializable.
    extra: Dict[str, Any] = field(default_factory=dict)

    # -------------------------------------------------------------- codec
    def to_payload(self) -> Dict[str, Any]:
        return {
            "step": int(self.step),
            "data_offsets": {str(k): int(v) for k, v in self.data_offsets.items()},
            "extra": dict(self.extra),
        }

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "ElasticState":
        return cls(
            step=int(payload.get("step", 0)),
            data_offsets={
                str(k): int(v)
                for k, v in (payload.get("data_offsets") or {}).items()
            },
            extra=dict(payload.get("extra") or {}),
        )

    def dumps(self) -> str:
        return json.dumps(self.to_payload())

    @classmethod
    def loads(cls, raw: str) -> "ElasticState":
        return cls.from_payload(json.loads(raw))

    # ------------------------------------------------------------ helpers
    def local_offset(self, name: str, rank: int, world_size: int) -> int:
        """Rank r's first sample index for dataset `name` under a
        rank-strided (round-robin) sharding: global samples are dealt
        rank, rank+W, rank+2W, ... — world-size changes just change the
        stride, never skip or replay a sample."""
        base = int(self.data_offsets.get(name, 0))
        # First global index not yet consumed is `base`; rank r's next
        # sample is the smallest i >= base with i % world_size == rank.
        rem = (rank - base) % world_size
        return base + rem

    def advance(self, name: str, consumed_global: int) -> None:
        self.data_offsets[name] = (
            int(self.data_offsets.get(name, 0)) + int(consumed_global)
        )

    # ------------------------------------------------------- MPMD pipeline
    def record_pipeline(
        self, stage: int, num_stages: int, num_chunks: int = 1
    ) -> None:
        """Stamp the pipeline position this shard belongs to. dp width is
        deliberately NOT recorded as a constraint — reshapes change it and
        the axis-0 reshard absorbs that — but the STAGE SPLIT (stages AND
        interleaved chunks: both change the flat-space layout) must match
        on restore: a stage-1-of-2 optimizer shard loaded into stage 1 of
        3 would silently install the wrong slice of the model."""
        self.extra["pipeline"] = {
            "stage": int(stage),
            "num_stages": int(num_stages),
            "num_chunks": int(num_chunks),
        }

    def check_pipeline(
        self, stage: int, num_stages: int, num_chunks: int = 1
    ) -> None:
        got = self.extra.get("pipeline")
        if got is None:
            return  # pre-MPMD checkpoint: nothing to validate against
        want = (int(stage), int(num_stages), int(num_chunks))
        # Checkpoints written before interleaving existed carry no chunk
        # count — they are v=1 by construction.
        have = (
            int(got.get("stage", -1)),
            int(got.get("num_stages", -1)),
            int(got.get("num_chunks", 1)),
        )
        if have != want:
            raise ValueError(
                f"checkpoint belongs to stage {got.get('stage')}/"
                f"{got.get('num_stages')} (x{got.get('num_chunks', 1)} "
                f"chunks) but is being restored into stage "
                f"{stage}/{num_stages} (x{num_chunks} chunks) — stage "
                "splits cannot change across a reshape (only dp width can)"
            )
