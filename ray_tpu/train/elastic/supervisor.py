"""Gang supervisor — death detection, mesh abort, restart policy.

Plane 1 of the elastic-training subsystem (ISSUE 4). The supervisor sits on
the driver next to BackendExecutor and answers three questions:

1. **Did a gang member die?** It subscribes to the controller's timeline
   through `poll_events` (the same feed `_on_actor_worker_death` writes
   `actor_restarting`/`actor_death` into — the actor-restart notification
   path reused, per the Ray paper's supervisor pattern, arXiv 1712.05889)
   and filters for the watched actor ids. Local mode has no controller —
   there the executor's poll loop (worker errors / failed actor calls) is
   the only detector, which is enough because local actors cannot be
   SIGKILLed independently anyway.
2. **How do we abort the whole mesh within the deadline?** Interrupt the
   collective first (`abort_collective_group` releases every member blocked
   in a rendezvous round instead of letting them wait out the full round
   timeout on a dead peer), then kill the surviving member actors, then
   tear down the worker group/placement group.
3. **Restart, shrink, or give up?** A capped restart budget
   (FailureConfig.max_failures) with exponential backoff
   (backoff_base_s * 2**attempt, capped at backoff_max_s); the new world
   size is chosen inside the ScalingConfig elasticity band
   [min_workers, max_workers] from currently-feasible capacity.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Set

from ..config import FailureConfig, ScalingConfig

# Timeline event kinds that mean "a watched gang member (or its host) is
# gone". node_died / chaos_worker_killed carry node/worker ids, matched
# against the gang placement resolved at watch() time — they fire a hair
# earlier than the per-actor events the same death eventually produces.
DEATH_EVENT_KINDS = (
    "actor_restarting",
    "actor_death",
    "chaos_worker_killed",
    "node_died",
)

_POLL_PERIOD_S = 0.1


@dataclass
class RestartDecision:
    stop: bool
    backoff_s: float = 0.0
    reason: str = ""


class GangSupervisor:
    """One instance per BackendExecutor.run(); watch() re-arms it on every
    gang (re)start."""

    def __init__(
        self,
        scaling: ScalingConfig,
        failure_config: Optional[FailureConfig] = None,
        experiment_name: str = "train",
    ):
        self.scaling = scaling
        # The band is snapshotted from the CONFIGURED scaling: run()
        # replaces scaling.num_workers on a shrink, and deriving the
        # ceiling from the mutated value would ratchet the gang down
        # permanently — a recovered node could never grow it back.
        self._band = scaling.elastic_band()
        self.failure_cfg = failure_config or FailureConfig()
        self.experiment_name = experiment_name
        self.attempts = 0
        self.last_recovery_s: Optional[float] = None
        self._actor_hexes: Set[str] = set()
        self._member_workers: Set[str] = set()
        self._member_nodes: Set[str] = set()
        self._cursor = -1
        self._failure_reason: Optional[str] = None
        self._failure_evt = threading.Event()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._collective_group: Optional[str] = None

    # ------------------------------------------------------------ watching
    def watch(self, worker_group, collective_group: Optional[str] = None):
        """Arm the supervisor on a (re)formed gang: remember the member
        actor ids and start the event-feed monitor (cluster mode only)."""
        self.stop_watch()
        self._collective_group = collective_group or self._collective_group
        ids = getattr(worker_group, "actor_ids", None)
        self._actor_hexes = set(ids() if ids else ())
        self._member_workers = set()
        self._member_nodes = set()
        self._failure_reason = None
        self._failure_evt.clear()
        backend = self._backend()
        if backend is None or not hasattr(backend, "poll_events"):
            return  # local mode: executor-poll detection only
        try:
            # Gang placement, so worker/node-level death events can be
            # scoped to THIS gang (an unrelated node scaling down must not
            # abort a healthy mesh; a member's node death is just detected
            # earlier than its actor_death).
            for a in backend._request({"type": "list_actors"})["actors"]:
                if a.get("actor_id") in self._actor_hexes:
                    if a.get("worker_id"):
                        self._member_workers.add(a["worker_id"])
                    if a.get("node_id"):
                        self._member_nodes.add(a["node_id"])
        except Exception:  # noqa: BLE001 — placement scoping is best-effort
            pass
        try:  # subscribe from the current tail
            self._cursor = backend.poll_events(cursor=-1)["cursor"]
        except Exception:  # noqa: BLE001 — controller mid-restart
            self._cursor = -1
        # Each arm gets a FRESH stop event: stop_watch's join is bounded
        # (2s), so a previous monitor can still be blocked inside an
        # unbounded poll_events RPC when the next watch() arms — clearing a
        # shared event would revive that zombie alongside the new monitor.
        # The old thread keeps its own (set) event and exits on return.
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._monitor,
            args=(self._stop_evt,),
            name="gang-supervisor",
            daemon=True,
        )
        self._thread.start()

    def stop_watch(self):
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None

    def _backend(self):
        from ...core import api

        rt = api._runtime_if_initialized()
        return rt.backend if rt is not None else None

    def _monitor(self, stop_evt: threading.Event):
        backend = self._backend()
        # Snapshot the gang this thread watches: watch() replaces the
        # instance-level sets when the NEXT incarnation arms, and a
        # straggler thread must not report a stale death against it.
        actor_hexes = set(self._actor_hexes)
        member_nodes = set(self._member_nodes)
        member_workers = set(self._member_workers)
        cursor = self._cursor
        while not stop_evt.is_set():
            try:
                resp = backend.poll_events(
                    cursor=cursor, kinds=DEATH_EVENT_KINDS
                )
            except Exception:  # noqa: BLE001 — controller unreachable: the
                # head may be mid-failover (docs/CONTROL_PLANE_HA.md). The
                # backend reconnects underneath us with its own backoff;
                # this cursor survives the restart because poll_events
                # re-anchors a previous incarnation's cursor to the NEW
                # timeline's base server-side (deaths landing during the
                # gap still arrive) — re-arm nothing, just retry.
                if stop_evt.wait(_POLL_PERIOD_S * 5):
                    return
                continue
            cursor = resp.get("cursor", cursor)
            for ev in resp.get("events", ()):
                kind = ev.get("event")
                actor = ev.get("actor")
                hit = (
                    (actor and actor in actor_hexes)
                    or (kind == "node_died"
                        and ev.get("node") in member_nodes)
                    or (kind == "chaos_worker_killed"
                        and ev.get("worker") in member_workers)
                )
                if hit and not stop_evt.is_set():
                    self._failure_reason = (
                        f"{kind}: "
                        f"{actor or ev.get('node') or ev.get('worker', '?')}"
                    )
                    self._failure_evt.set()
                    return
            if stop_evt.wait(_POLL_PERIOD_S):
                return

    def failure(self) -> Optional[str]:
        """Non-blocking: the detected death (as a reason string), or None."""
        return self._failure_reason if self._failure_evt.is_set() else None

    # -------------------------------------------------------------- abort
    def abort_mesh(self, worker_group) -> float:
        """Abort the ENTIRE mesh: interrupt in-flight collectives, kill every
        member, drop the placement group. Returns seconds taken; logs a
        deadline breach (the deadline bounds the wedge, it cannot hard-stop
        a teardown that is already past it)."""
        t0 = time.monotonic()
        self.stop_watch()
        if self._collective_group:
            from ... import collective

            # One group (data-parallel gang) or a list of them (MPMD: one
            # dp group per pipeline stage) — every group a surviving member
            # could be blocked in gets aborted.
            groups = (
                self._collective_group
                if isinstance(self._collective_group, (list, tuple))
                else [self._collective_group]
            )
            for g in groups:
                collective.abort_collective_group(
                    g, timeout=self.failure_cfg.abort_deadline_s,
                )
        if worker_group is not None:
            worker_group.shutdown()
        took = time.monotonic() - t0
        if took > self.failure_cfg.abort_deadline_s:
            import logging

            logging.getLogger(__name__).warning(
                "gang abort took %.1fs (deadline %.1fs)",
                took, self.failure_cfg.abort_deadline_s,
            )
        return took

    # ------------------------------------------------------------- policy
    def feasible_workers(self) -> Optional[int]:
        """How many workers the cluster could place right now, from
        available CPU/TPU vs the per-worker ask. None when unknowable
        (no cluster backend)."""
        backend = self._backend()
        if backend is None or not hasattr(backend, "available_resources"):
            return None
        try:
            avail = backend.available_resources()
        except Exception:  # noqa: BLE001
            return None
        need = self.scaling.worker_resources()
        counts = []
        for res, per in need.items():
            if per <= 0:
                continue
            counts.append(int(avail.get(res, 0.0) // per))
        return min(counts) if counts else None

    def on_failure(self, reason: str) -> RestartDecision:
        """Consume one unit of restart budget and decide restart vs stop.
        The new world size is NOT chosen here: right after abort_mesh the
        just-killed survivors' resources are still draining on the
        controller, so a feasibility reading now would spuriously shrink
        the gang to the band floor — the executor calls plan_world_size()
        after the backoff sleep instead."""
        self.attempts += 1
        budget = self.failure_cfg.max_failures
        if budget >= 0 and self.attempts > budget:
            return RestartDecision(stop=True, reason=reason)
        backoff = min(
            self.failure_cfg.backoff_base_s * (2 ** (self.attempts - 1)),
            self.failure_cfg.backoff_max_s,
        )
        return RestartDecision(stop=False, backoff_s=backoff, reason=reason)

    def plan_world_size(self) -> int:
        """World size for the next incarnation, from the ORIGINAL
        elasticity band and capacity measured NOW (call after the backoff,
        when the dead gang's resources have been released). Growth back up
        to the configured ceiling happens here too, once capacity
        returns."""
        return self.scaling.pick_world_size(
            self.feasible_workers(), band=self._band
        )

    def record_recovery(self, seconds: float):
        """Count the restart + observe death-to-reformed-gang MTTR."""
        self.last_recovery_s = seconds
        try:
            from ...util.metrics import elastic_metrics

            m = elastic_metrics()
            tags = {"experiment": self.experiment_name}
            m["elastic_restarts_total"].inc(1.0, tags=tags)
            m["elastic_recovery_seconds"].observe(seconds, tags=tags)
        except Exception:  # noqa: BLE001 — metrics never load-bearing
            pass
