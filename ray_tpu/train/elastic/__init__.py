"""ray_tpu.train.elastic — fault-tolerant gang training.

Three planes (ISSUE 4; TorchTitan arXiv 2410.06511 + the Ray paper's
supervisor pattern, arXiv 1712.05889):

- `supervisor.GangSupervisor` — watches controller death events, aborts the
  whole mesh on any member death, decides restart/shrink/stop with a capped
  budget and exponential backoff. Driven by `BackendExecutor.run()`.
- `ckpt.AsyncShardWriter` / `ckpt.ShardedCheckpoint` — per-rank background
  shard writes with a group-commit marker; crash mid-save leaves the
  previous committed checkpoint restorable; restore reshards on world-size
  change.
- `state.ElasticState` — step counter + global data offsets travel with the
  checkpoint so the resumed loss trajectory matches an unkilled run.

Worker-side usage, inside `train_loop_per_worker`:

    from ray_tpu.train import elastic

    sess = elastic.elastic_session()          # binds rank/world/storage
    tree = sess.restore() or init_tree()      # None on a fresh run
    for step in range(sess.state.step, total_steps):
        tree = train_step(tree, batch_at(sess.state, step))
        sess.save(step + 1, tree)             # async; never blocks the step
    sess.flush()

See docs/ELASTIC_TRAINING.md for the failure model and every knob.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional

from .ckpt import (
    AsyncShardWriter,
    COMMIT_MARKER,
    ShardedCheckpoint,
    latest_common_committed,
    stage_root,
)
from .state import ElasticState
from .supervisor import DEATH_EVENT_KINDS, GangSupervisor, RestartDecision

# Env var carrying the gang-incarnation token (set by BackendExecutor.start
# on every (re)start; all ranks of one incarnation share it so their shards
# land in the same checkpoint directory and never mix with a previous
# incarnation's partial save).
GEN_ENV = "RAY_TPU_TRAIN_ELASTIC_GEN"
# Run-identity namespace (set once per BackendExecutor): unnamed runs share
# the default resolved storage path, and without this token a brand-new run
# would silently restore a PREVIOUS run's committed checkpoints — wrong
# weights and a wrong step counter. Named runs carry their name (stable, so
# elastic resume across driver restarts stays possible by opting into a
# RunConfig name).
RUN_ENV = "RAY_TPU_TRAIN_ELASTIC_RUN"


class ElasticSession:
    """Per-rank elastic checkpoint/restore surface, bound to the ambient
    train session (rank, world size, storage path, incarnation token)."""

    def __init__(
        self,
        root: Optional[str] = None,
        mode: str = "replicated",
        queue_depth: int = 2,
        keep: Optional[int] = 3,
    ):
        # Default mode is "replicated" because DataParallelTrainer (the
        # trainer this session runs under) keeps identical params on every
        # rank: restore after an elastic world-size change takes rank 0's
        # copy. mode="sharded" is for trees that genuinely are axis-0
        # partitions (FSDP-style) — concatenating REPLICATED trees on a
        # shrink would duplicate every weight. Commit is group-wide in both
        # modes (marker requires every rank's shard).
        from ..session import get_context

        ctx = get_context()
        self.rank = ctx.get_world_rank()
        self.world_size = ctx.get_world_size()
        # Both defaults are namespaced by the run token (or experiment
        # name when running outside the trainer) — a fixed shared path
        # would let unrelated runs cross-restore each other's checkpoints
        # (wrong weights AND a wrong step counter).
        run_ns = (
            ctx.env_vars.get(RUN_ENV)
            or os.environ.get(RUN_ENV)
            or ctx.get_experiment_name()
            or "default"
        )
        storage = root or (
            os.path.join(ctx.get_storage(), "elastic", run_ns)
            if ctx.get_storage()
            else os.path.join(
                tempfile.gettempdir(), f"rtpu-elastic-{run_ns}"
            )
        )
        self.root = storage
        gen = (
            ctx.env_vars.get(GEN_ENV)
            or os.environ.get(GEN_ENV)
            or "0"
        )
        self.state = ElasticState()
        tags = (
            {"experiment": ctx.get_experiment_name()}
            if ctx.get_experiment_name()
            else {}
        )
        self.writer = AsyncShardWriter(
            storage, self.rank, self.world_size, gen=gen, mode=mode,
            queue_depth=queue_depth, metric_tags=tags, keep=keep,
        )

    # ------------------------------------------------------------ restore
    def restore(self) -> Optional[Any]:
        """Load the latest committed checkpoint (resharding if the saved
        world size differs); installs its ElasticState on `self.state` and
        returns the tree — or None on a fresh run (state stays zeroed)."""
        found = ShardedCheckpoint.restore(self.root, self.rank, self.world_size)
        if found is None:
            return None
        self.state, tree = found
        return tree

    # --------------------------------------------------------------- save
    def save(
        self,
        step: int,
        tree: Any,
        data_offsets: Optional[Dict[str, int]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Async checkpoint: snapshot + enqueue, return immediately. `step`
        is the NEXT step to run on resume (save(step + 1, ...) after
        finishing step)."""
        self.state.step = int(step)
        if data_offsets is not None:
            self.state.data_offsets.update(
                {str(k): int(v) for k, v in data_offsets.items()}
            )
        if extra is not None:
            self.state.extra.update(extra)
        self.writer.save(step, tree, self.state)

    def flush(self, timeout: float = 60.0) -> bool:
        return self.writer.flush(timeout)

    def close(self) -> None:
        self.writer.close()


def elastic_session(**kwargs) -> ElasticSession:
    """The session-cached ElasticSession for this training worker (one per
    incarnation; repeated calls return the same instance). Must be called
    from inside `train_loop_per_worker`. Raises when `kwargs` conflict
    with the cached session's construction parameters — silently handing a
    `mode='sharded'` caller a cached replicated-mode session would commit
    FSDP-style partitions under mode='replicated' meta, and a later
    world-size-changed restore would replace every rank's partition with
    rank 0's, corrupting the model with no error."""
    from ..session import get_session

    s = get_session()
    if s is None:
        raise RuntimeError(
            "elastic_session() called outside a training worker"
        )
    es = getattr(s, "elastic", None)
    if es is None:
        es = ElasticSession(**kwargs)
        s.elastic = es
    else:
        effective = {
            "root": es.root,
            "mode": es.writer.mode,
            "queue_depth": es.writer._q.maxsize,
            "keep": es.writer.keep,
        }
        for k, v in kwargs.items():
            if k == "root" and v is None:
                continue
            if k == "queue_depth":
                v = max(1, v)  # the writer clamps its queue the same way
            if k in effective and effective[k] != v:
                raise RuntimeError(
                    f"elastic_session({k}={v!r}) conflicts with the "
                    f"already-created session's {k}={effective[k]!r}; the "
                    "first call in the loop fixes the parameters"
                )
    return es


__all__ = [
    "AsyncShardWriter",
    "ShardedCheckpoint",
    "COMMIT_MARKER",
    "stage_root",
    "latest_common_committed",
    "ElasticState",
    "ElasticSession",
    "elastic_session",
    "GangSupervisor",
    "RestartDecision",
    "DEATH_EVENT_KINDS",
    "GEN_ENV",
]
