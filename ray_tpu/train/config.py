"""Run/scaling/checkpoint/failure configs (reference: `python/ray/air/config.py`)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many workers and what each needs.

    Reference analog: `air/config.py ScalingConfig` (num_workers,
    use_gpu, resources_per_worker). TPU addition: `topology` — a mesh axis
    dict (e.g. {"dp": 4, "tp": 4}) describing the global device mesh the
    worker gang should assemble.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    topology: Optional[Dict[str, int]] = None
    # Elasticity band (train/elastic): on a gang restart the supervisor may
    # re-form the gang with any world size in [min_workers, max_workers]
    # when the full `num_workers` gang is infeasible (capacity lost with a
    # node, say). None/None disables shrinking — restarts always demand the
    # original world size.
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    def elastic_band(self) -> "tuple[int, int]":
        """(lo, hi) world-size band, clamped to sane values. `is None`
        checks, not truthiness: min_workers=0 means "shrink to any size",
        not "band unset"."""
        hi = self.num_workers if self.max_workers is None else self.max_workers
        lo = hi if self.min_workers is None else self.min_workers
        lo = max(1, min(lo, hi))
        return lo, hi

    def pick_world_size(
        self,
        feasible: Optional[int],
        band: "Optional[tuple[int, int]]" = None,
    ) -> int:
        """World size for a (re)start given `feasible` workers' worth of
        capacity (None = unknown → demand the full band top). `band`
        overrides elastic_band(): pass a snapshot taken from the ORIGINAL
        config when (like BackendExecutor.run) the caller mutates
        num_workers on a shrink — deriving the ceiling from the mutated
        value would ratchet the gang down permanently."""
        lo, hi = band if band is not None else self.elastic_band()
        if feasible is None:
            return hi
        return max(lo, min(hi, feasible))

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        if "CPU" not in res:
            res["CPU"] = 1.0
        return res


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class FailureConfig:
    """Gang failure policy (consumed by train/elastic's GangSupervisor).

    max_failures: restart budget — how many gang restarts before the run
        surfaces the error (-1 = unbounded). 0 keeps the legacy behavior:
        first failure is final.
    abort_deadline_s: after a member death the whole mesh must be aborted
        (collectives interrupted, surviving members torn down) within this
        many seconds — a wedged barrier past the deadline is a bug.
    backoff_base_s / backoff_max_s: exponential backoff between gang
        restarts: min(backoff_base_s * 2**attempt, backoff_max_s).
    """

    max_failures: int = 0
    abort_deadline_s: float = 10.0
    backoff_base_s: float = 0.25
    backoff_max_s: float = 15.0


@dataclass
class DataConfig:
    datasets_to_split: Any = "all"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    stop: Optional[dict] = None
    verbose: int = 1

    def resolve_storage(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )
        name = self.name or "run"
        return os.path.join(base, name)
