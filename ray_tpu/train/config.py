"""Run/scaling/checkpoint/failure configs (reference: `python/ray/air/config.py`)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many workers and what each needs.

    Reference analog: `air/config.py ScalingConfig` (num_workers,
    use_gpu, resources_per_worker). TPU addition: `topology` — a mesh axis
    dict (e.g. {"dp": 4, "tp": 4}) describing the global device mesh the
    worker gang should assemble.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    topology: Optional[Dict[str, int]] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        if "CPU" not in res:
            res["CPU"] = 1.0
        return res


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class DataConfig:
    datasets_to_split: Any = "all"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    stop: Optional[dict] = None
    verbose: int = 1

    def resolve_storage(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )
        name = self.name or "run"
        return os.path.join(base, name)
