"""Checkpoint: a directory handle with to/from-pytree helpers.

Reference analog: `ray.train.Checkpoint` (`python/ray/air/checkpoint.py`) —
a movable directory. TPU addition: orbax-backed pytree save/restore so
sharded jax arrays round-trip correctly (reference uses torch.save).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = path

    # ----------------------------------------------------------- factories
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="rtpu-ckpt-")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        """Save a jax pytree (uses orbax when available, pickle otherwise)."""
        d = path or tempfile.mkdtemp(prefix="rtpu-ckpt-")
        os.makedirs(d, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            target = os.path.join(os.path.abspath(d), "pytree")
            if os.path.exists(target):
                shutil.rmtree(target)
            ckptr.save(target, tree)
            ckptr.wait_until_finished()
        except Exception:  # noqa: BLE001 — orbax absent or type unsupported
            import jax

            host_tree = jax.tree_util.tree_map(lambda x: _to_host(x), tree)
            with open(os.path.join(d, "pytree.pkl"), "wb") as f:
                pickle.dump(host_tree, f)
        return cls(d)

    # ------------------------------------------------------------ accessors
    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def to_pytree(self, template: Any = None) -> Any:
        orbax_path = os.path.join(self.path, "pytree")
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            return ckptr.restore(os.path.abspath(orbax_path), template)
        with open(os.path.join(self.path, "pytree.pkl"), "rb") as f:
            return pickle.load(f)

    def as_directory(self) -> str:
        return self.path

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def _to_host(x):
    try:
        import numpy as np

        return np.asarray(x)
    except Exception:  # noqa: BLE001
        return x


MANAGER_COMMIT_MARKER = ".committed"


class CheckpointManager:
    """Keeps top-k checkpoints by score (reference:
    `train/_internal/checkpoint_manager.py`).

    Registration is crash-safe: the incoming checkpoint is copied to a
    `.tmp` sibling, fsynced, and atomically renamed into place — the commit
    marker (written before the rename, so a renamed dir always carries it)
    is what `resume_latest` trusts; a crash mid-copy leaves only a `.tmp`
    dir that no resume path ever reads. Eviction of the displaced top-k
    entry happens only AFTER the new checkpoint has committed."""

    def __init__(self, directory: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # (score, path, metrics, order)
        # Adopt a previous process's checkpoints: a resumed run registering
        # from 1 would rmtree the dead run's committed checkpoint_000001
        # and leave resume_latest() preferring the dead run's higher
        # numbers over the live run's fresh checkpoints — and entries left
        # out of the table would be invisible to _evict, stranding up to
        # num_to_keep extra dirs per restart forever. Adoption informs
        # NUMBERING and EVICTION only: latest()/best() see this process's
        # registrations, so a fresh run in a reused directory never has a
        # mid-run failure silently restore the previous run's weights
        # (cross-process resume stays explicit, via resume_latest()).
        self._adopted_through = 0  # orders <= this are adopted, not ours
        self._counter = 0
        for order, path, committed in _scan_checkpoints(directory):
            self._counter = max(self._counter, order)
            if not committed:
                continue  # uncommitted: not a checkpoint (resume_latest agrees)
            metrics: Dict[str, Any] = {}
            try:
                with open(os.path.join(path, "metrics.json")) as f:
                    metrics = json.load(f).get("metrics", {})
            except (OSError, ValueError):
                pass
            score = (
                metrics.get(self.score_attribute)
                if self.score_attribute
                else order
            )
            self._entries.append((score, path, metrics, order))
        self._adopted_through = self._counter

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> str:
        self._counter += 1
        dest = os.path.join(self.directory, f"checkpoint_{self._counter:06d}")
        meta = json.dumps({"metrics": _json_safe(metrics), "ts": time.time()})
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
            tmp = dest + ".tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            shutil.copytree(checkpoint.path, tmp)
            _write_file_synced(os.path.join(tmp, "metrics.json"), meta)
            _write_file_synced(os.path.join(tmp, MANAGER_COMMIT_MARKER), "")
            _fsync_tree(tmp)
            if os.path.exists(dest):  # stale dir from a crashed predecessor
                shutil.rmtree(dest)
            os.rename(tmp, dest)
            _fsync_dir(self.directory)
        else:
            _write_file_synced(os.path.join(dest, "metrics.json"), meta)
            # Same durability barrier as the copy branch: the payload must
            # be on disk BEFORE the marker makes resume_latest() trust it.
            _fsync_tree(dest)
            _write_file_synced(os.path.join(dest, MANAGER_COMMIT_MARKER), "")
            _fsync_dir(self.directory)
        score = metrics.get(self.score_attribute) if self.score_attribute else self._counter
        self._entries.append((score, dest, dict(metrics), self._counter))
        # Only now — with the new checkpoint durably committed — may the
        # displaced top-k entry be evicted (evicting first would leave zero
        # restorable checkpoints if the copy crashed).
        self._evict()
        return dest

    def _ranked(self):
        """Entries best-first; missing scores always rank WORST; score ties
        break toward the NEWER registration (ties must not evict the most
        recent checkpoint — it is what resume paths want)."""
        reverse = self.score_order == "max"
        if reverse:
            key = lambda e: (e[0] is not None, e[0] if e[0] is not None else 0, e[3])  # noqa: E731
        else:
            key = lambda e: (e[0] is None, e[0] if e[0] is not None else 0, -e[3])  # noqa: E731
        return sorted(self._entries, key=key, reverse=reverse)

    def _evict(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        ranked = self._ranked()
        keep = ranked[: self.num_to_keep]
        # The newest OWN registration is never evicted: latest() excludes
        # adopted entries, so letting a better-scored adopted checkpoint
        # displace this run's only registration would leave latest()=None
        # (and register() returning an already-deleted path) — a restart
        # would silently lose all of this run's progress.
        own = self._own()
        newest_own = max(own, key=lambda e: e[3]) if own else None
        if newest_own is not None and newest_own not in keep:
            keep = keep[:-1] + [newest_own]
        for entry in self._entries:
            if entry not in keep:
                shutil.rmtree(entry[1], ignore_errors=True)
        # Preserve registration order so latest() means "most recent", not
        # "lowest-ranked survivor".
        self._entries = sorted(keep, key=lambda e: e[3])

    def _own(self):
        """This process's registrations (adopted entries excluded)."""
        return [e for e in self._entries if e[3] > self._adopted_through]

    def best(self) -> Optional[Checkpoint]:
        own = self._own()
        if not own:
            return None
        ranked = [e for e in self._ranked() if e[3] > self._adopted_through]
        return Checkpoint(ranked[0][1])

    def latest(self) -> Optional[Checkpoint]:
        own = self._own()
        if not own:
            return None
        return Checkpoint(max(own, key=lambda e: e[3])[1])


def _scan_checkpoints(directory: str):
    """Yield (order, path, committed) for every `checkpoint_NNNNNN` dir,
    ascending by order; `.tmp` staging dirs are skipped. The ONE place the
    manager-dir naming/commit protocol is parsed — CheckpointManager
    adoption and resume_latest() must never disagree about which
    checkpoints exist."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return
    for name in names:
        if not name.startswith("checkpoint_") or name.endswith(".tmp"):
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            order = int(name[len("checkpoint_"):])
        except ValueError:
            continue
        committed = os.path.exists(os.path.join(path, MANAGER_COMMIT_MARKER))
        yield order, path, committed


def resume_latest(directory: str) -> Optional[Checkpoint]:
    """Cross-process resume helper: newest COMMITTED checkpoint under a
    CheckpointManager directory. Skips `.tmp` dirs and any dir without the
    commit marker (a crash mid-registration) — those are not checkpoints,
    whatever their names claim."""
    best = None
    for order, path, committed in _scan_checkpoints(directory):
        if committed and (best is None or order > best[0]):
            best = (order, path)
    return Checkpoint(best[1]) if best else None


def _write_file_synced(path: str, data: str) -> None:
    with open(path, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_tree(root: str) -> None:
    """fsync every file + dir under root (pre-rename durability barrier)."""
    for dirpath, _, filenames in os.walk(root):
        for fn in filenames:
            try:
                fd = os.open(os.path.join(dirpath, fn), os.O_RDONLY)
            except OSError:
                continue
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        _fsync_dir(dirpath)


def _json_safe(d):
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = str(v)
    return out
