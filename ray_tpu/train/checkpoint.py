"""Checkpoint: a directory handle with to/from-pytree helpers.

Reference analog: `ray.train.Checkpoint` (`python/ray/air/checkpoint.py`) —
a movable directory. TPU addition: orbax-backed pytree save/restore so
sharded jax arrays round-trip correctly (reference uses torch.save).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = path

    # ----------------------------------------------------------- factories
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="rtpu-ckpt-")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        """Save a jax pytree (uses orbax when available, pickle otherwise)."""
        d = path or tempfile.mkdtemp(prefix="rtpu-ckpt-")
        os.makedirs(d, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            target = os.path.join(os.path.abspath(d), "pytree")
            if os.path.exists(target):
                shutil.rmtree(target)
            ckptr.save(target, tree)
            ckptr.wait_until_finished()
        except Exception:  # noqa: BLE001 — orbax absent or type unsupported
            import jax

            host_tree = jax.tree_util.tree_map(lambda x: _to_host(x), tree)
            with open(os.path.join(d, "pytree.pkl"), "wb") as f:
                pickle.dump(host_tree, f)
        return cls(d)

    # ------------------------------------------------------------ accessors
    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def to_pytree(self, template: Any = None) -> Any:
        orbax_path = os.path.join(self.path, "pytree")
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            return ckptr.restore(os.path.abspath(orbax_path), template)
        with open(os.path.join(self.path, "pytree.pkl"), "rb") as f:
            return pickle.load(f)

    def as_directory(self) -> str:
        return self.path

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def _to_host(x):
    try:
        import numpy as np

        return np.asarray(x)
    except Exception:  # noqa: BLE001
        return x


class CheckpointManager:
    """Keeps top-k checkpoints by score (reference:
    `train/_internal/checkpoint_manager.py`)."""

    def __init__(self, directory: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # (score, path, metrics, order)
        self._counter = 0

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> str:
        self._counter += 1
        dest = os.path.join(self.directory, f"checkpoint_{self._counter:06d}")
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, dest)
        score = metrics.get(self.score_attribute) if self.score_attribute else self._counter
        self._entries.append((score, dest, dict(metrics), self._counter))
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({"metrics": _json_safe(metrics), "ts": time.time()}, f)
        self._evict()
        return dest

    def _ranked(self):
        """Entries best-first; missing scores always rank WORST."""
        reverse = self.score_order == "max"
        if reverse:
            key = lambda e: (e[0] is not None, e[0] if e[0] is not None else 0)  # noqa: E731
        else:
            key = lambda e: (e[0] is None, e[0] if e[0] is not None else 0)  # noqa: E731
        return sorted(self._entries, key=key, reverse=reverse)

    def _evict(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        ranked = self._ranked()
        for _, path, _, _ in ranked[self.num_to_keep :]:
            shutil.rmtree(path, ignore_errors=True)
        kept = ranked[: self.num_to_keep]
        # Preserve registration order so latest() means "most recent", not
        # "lowest-ranked survivor".
        self._entries = sorted(kept, key=lambda e: e[3])

    def best(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(self._ranked()[0][1])

    def latest(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(max(self._entries, key=lambda e: e[3])[1])


def _json_safe(d):
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = str(v)
    return out
