"""GBDT trainers over Ray Data.

Reference analog: `python/ray/train/gbdt_trainer.py` (shared base of
`XGBoostTrainer` / `LightGBMTrainer`, `train/xgboost/xgboost_trainer.py`) —
the reference schedules external C++ boosters across a worker gang. TPU
redesign: the booster itself is JAX (`models/gbdt.py` — jitted histogram
rounds), so the same trainer surface runs on TPU/CPU with no external
dependency. `XGBoostTrainer` is an API-compatibility shim that translates
common xgboost param names onto `GBDTParams`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..models.gbdt import GBDTParams, GradientBoostedTrees
from .checkpoint import Checkpoint
from .config import RunConfig, ScalingConfig
from .data_parallel_trainer import DataParallelTrainer


def _materialize_xy(shard, label_column: str):
    """Dataset shard -> (X, y) numpy (GBDT fits in-memory per worker, like
    the reference's DMatrix build)."""
    feats, labels = [], []
    for batch in shard.iter_batches(batch_size=4096, batch_format="numpy"):
        y = batch.pop(label_column)
        cols = [np.asarray(batch[k], np.float32).reshape(len(y), -1)
                for k in sorted(batch)]
        feats.append(np.concatenate(cols, axis=1))
        labels.append(np.asarray(y, np.float32).ravel())
    return np.concatenate(feats), np.concatenate(labels)


def _gbdt_loop(config: Dict[str, Any]):
    from .. import train

    shard = train.get_dataset_shard("train")
    X, y = _materialize_xy(shard, config["label_column"])
    model = GradientBoostedTrees(config["gbdt_params"]).fit(X, y)
    metrics = {"train_loss": model.train_history[-1],
               "num_trees": int(model.trees["feat"].shape[0])}
    valid = train.get_dataset_shard("valid")
    if valid is not None:
        Xv, yv = _materialize_xy(valid, config["label_column"])
        pred = model.predict(Xv)
        if config["gbdt_params"].objective == "squared_error":
            metrics["valid_rmse"] = float(np.sqrt(np.mean((pred - yv) ** 2)))
        else:
            metrics["valid_logloss"] = float(
                -np.mean(yv * np.log(pred + 1e-9)
                         + (1 - yv) * np.log(1 - pred + 1e-9))
            )
            metrics["valid_accuracy"] = float(((pred > 0.5) == yv).mean())
    train.report(metrics, checkpoint=Checkpoint.from_dict(
        {"model": model.to_dict()}
    ))


class GBDTTrainer(DataParallelTrainer):
    """Fit a JAX histogram booster on a Ray Dataset.

        trainer = GBDTTrainer(
            datasets={"train": ds, "valid": vds},
            label_column="y",
            params=GBDTParams(objective="binary_logistic", max_depth=5),
        )
        result = trainer.fit()
        model = GradientBoostedTrees.from_dict(
            result.checkpoint.to_dict()["model"])
    """

    def __init__(
        self,
        *,
        datasets,
        label_column: str,
        params: Optional[GBDTParams] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        super().__init__(
            _gbdt_loop,
            train_loop_config={
                "label_column": label_column,
                "gbdt_params": params or GBDTParams(),
            },
            scaling_config=scaling_config or ScalingConfig(num_workers=1),
            run_config=run_config,
            datasets=datasets,
        )


_XGB_PARAM_MAP = {
    "eta": "learning_rate",
    "learning_rate": "learning_rate",
    "max_depth": "max_depth",
    "lambda": "reg_lambda",
    "reg_lambda": "reg_lambda",
    "gamma": "gamma",
    "min_child_weight": "min_child_weight",
    "base_score": "base_score",
    "max_bin": "max_bins",
}
_XGB_OBJECTIVES = {
    "reg:squarederror": "squared_error",
    "binary:logistic": "binary_logistic",
}


class XGBoostTrainer(GBDTTrainer):
    """xgboost-flavored surface (reference:
    `python/ray/train/xgboost/xgboost_trainer.py`) on the JAX booster —
    accepts the common subset of xgboost `params` plus
    `num_boost_round`."""

    def __init__(self, *, datasets, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 50, **kw):
        params = dict(params or {})
        obj = params.pop("objective", "reg:squarederror")
        if obj not in _XGB_OBJECTIVES:
            raise ValueError(
                f"objective {obj!r} not supported (have: "
                f"{sorted(_XGB_OBJECTIVES)})"
            )
        mapped: Dict[str, Any] = {"objective": _XGB_OBJECTIVES[obj],
                                  "num_boost_round": num_boost_round}
        for k, v in params.items():
            if k not in _XGB_PARAM_MAP:
                raise ValueError(f"unsupported xgboost param {k!r}")
            mapped[_XGB_PARAM_MAP[k]] = v
        super().__init__(
            datasets=datasets, label_column=label_column,
            params=GBDTParams(**mapped), **kw,
        )
