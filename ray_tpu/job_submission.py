"""Job submission SDK (reference: `dashboard/modules/job/sdk.py:39`
`JobSubmissionClient` — submit_job `:129`, job run as a supervised driver
subprocess on the cluster; status/logs/stop round-trips).

    client = JobSubmissionClient()            # session_latest discovery
    job_id = client.submit_job(entrypoint="python my_train.py",
                               runtime_env={"env_vars": {"MODE": "prod"}})
    client.get_job_status(job_id)             # RUNNING/SUCCEEDED/FAILED/STOPPED
    print(client.get_job_logs(job_id))
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None):
        if address is None:
            address = os.environ.get("RAY_TPU_ADDRESS")
        if address is None:
            with open("/tmp/ray_tpu/session_latest/address.json") as f:
                info = json.load(f)
            address = info["address"]
            from .core.rpc import adopt_auth_token

            adopt_auth_token(info.get("auth_token", ""))
        from .core.cluster_backend import ClusterBackend

        self._backend = ClusterBackend(address)
        self._backend._connect(register_as="register_client")

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        submission_id: Optional[str] = None,  # accepted for API parity
    ) -> str:
        resp = self._backend._request(
            {"type": "submit_job", "entrypoint": entrypoint, "runtime_env": runtime_env}
        )
        if resp.get("error"):
            raise RuntimeError(f"job submission failed: {resp['error']}")
        return resp["job_id"]

    def get_job_status(self, job_id: str) -> str:
        resp = self._backend._request({"type": "job_status", "job_id": job_id})
        if resp.get("error"):
            raise ValueError(resp["error"])
        return resp["status"]

    def get_job_info(self, job_id: str) -> Dict:
        resp = self._backend._request({"type": "job_status", "job_id": job_id})
        if resp.get("error"):
            raise ValueError(resp["error"])
        return resp

    def list_jobs(self) -> List[Dict]:
        return self._backend._request({"type": "list_jobs"})["jobs"]

    def get_job_logs(self, job_id: str) -> str:
        resp = self._backend._request({"type": "job_logs", "job_id": job_id})
        if resp.get("error"):
            raise ValueError(resp["error"])
        return resp["data"]

    def stop_job(self, job_id: str) -> bool:
        return self._backend._request({"type": "stop_job", "job_id": job_id})["ok"]

    def wait_until_finish(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")

    def close(self):
        self._backend.conn.close()
        self._backend.io.stop()
