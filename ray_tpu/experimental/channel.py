"""Reusable shared-memory channels (reference: `python/ray/experimental/channel.py:49,99,135`
`Channel.write/begin_read`).

The reference reuses one mmap'd plasma buffer per edge of a compiled DAG so
steady-state execution does zero allocations and zero task submissions. Same
design here: a named POSIX shm segment with a seqlock header, single writer,
N readers; the writer blocks until every reader has acked the previous
message (backpressure = buffer reuse safety).

Header layout (little-endian u64s):
    [0]            seq     — message sequence number, bumped after payload is in place
    [8]            length  — payload byte length
    [16]           flag    — 0 normal, 1 stop sentinel
    [24 + 8*k]     ack_k   — last seq acked by reader slot k (k < num_readers)

Each reader owns a distinct ack slot and writes its *absolute* last-read seq
(idempotent store, no read-modify-write) — concurrent acks from readers in
different processes cannot race.

Growth: a payload larger than the buffer used to fail the write outright
(the compiled-DAG 1 MiB default was a hard ceiling). Channels are now
growable by default: the writer allocates a fresh, larger segment, announces
it with a RELOCATE message (flag 2, payload = new segment name) through the
old segment, waits for every reader slot to ack the relocation, then
publishes the oversized payload in the new segment (sequence numbers restart
at 0 there — both sides reset together, so the seqlock protocol is
unchanged). Readers follow the forward pointer transparently inside
begin_read. The relocated-from segment is unlinked by its owner (writer if
it created it, else the creator's destroy()/resource tracker); grown
segments are owned by the writer process that created them.
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_FLAG_STOP = 1
_FLAG_RELOC = 2


class ChannelClosed(Exception):
    pass


class Channel:
    def __init__(
        self,
        buffer_size: int = 1 << 20,
        *,
        name: Optional[str] = None,
        create: bool = True,
        num_readers: int = 1,
        reader_slot: int = 0,
        growable: bool = True,
    ):
        self.num_readers = num_readers
        self.reader_slot = reader_slot
        self.growable = growable
        self._header = 24 + 8 * num_readers
        if create:
            # Creator stays tracker-registered: unlink() (ours in destroy(),
            # or the tracker's at process exit for leaked channels) balances
            # the registration.
            self._shm = shared_memory.SharedMemory(
                create=True, size=self._header + buffer_size, name=name
            )
            self._shm.buf[: self._header] = b"\0" * self._header
        else:
            # Attach WITHOUT tracker registration: forked workers share the
            # parent's resource tracker, and duplicate unregisters for the
            # same segment name crash the tracker daemon at exit.
            self._shm = _attach_untracked(name)
        self._owner = create
        self._last_read_seq = 0
        self._bind_native()

    def _bind_native(self):
        """Hot wait/copy ops run in C++ when the native lib builds (proper
        acquire/release atomics + adaptive spin instead of a 500µs poll);
        same header layout, so native and Python ends interoperate."""
        self._native = None
        self._base_addr = 0
        try:
            from ..native import load_channel_lib

            lib = load_channel_lib()
            if lib is not None:
                import ctypes

                self._native = lib
                self._base_addr = ctypes.addressof(
                    ctypes.c_char.from_buffer(self._shm.buf)
                )
        except Exception:  # noqa: BLE001 — fall back to pure Python
            self._native = None

    @property
    def name(self) -> str:
        return self._shm.name

    def with_reader_slot(self, slot: int) -> "Channel":
        """A view of this channel for reader slot `slot` (what you ship to
        the consumer process)."""
        if not 0 <= slot < self.num_readers:
            raise ValueError(f"reader slot {slot} out of range [0, {self.num_readers})")
        ch = Channel.__new__(Channel)
        ch.num_readers = self.num_readers
        ch.reader_slot = slot
        ch.growable = self.growable
        ch._header = self._header
        ch._shm = self._shm
        ch._owner = False
        ch._last_read_seq = self._last_read_seq
        ch._native = self._native
        ch._base_addr = self._base_addr
        return ch

    # ------------------------------------------------------------- header
    def _get(self, off: int) -> int:
        return struct.unpack_from("<Q", self._shm.buf, off)[0]

    def _set(self, off: int, val: int):
        struct.pack_into("<Q", self._shm.buf, off, val)

    def _min_ack(self) -> int:
        return min(self._get(24 + 8 * k) for k in range(self.num_readers))

    # -------------------------------------------------------------- write
    def write(self, value: Any, timeout: Optional[float] = 60.0):
        self._write_payload(pickle.dumps(value), 0, timeout)

    def _write_payload(self, payload: bytes, flag: int, timeout: Optional[float]):
        if len(payload) > len(self._shm.buf) - self._header:
            if not self.growable or flag != 0:
                raise ValueError(
                    f"Serialized value ({len(payload)}B) exceeds channel buffer "
                    f"({len(self._shm.buf) - self._header}B); recreate the DAG "
                    "with a larger _buffer_size_bytes"
                )
            self._relocate(len(payload), timeout)
        if self._native is not None:
            timeout_us = -1 if timeout is None else int(timeout * 1e6)
            rc = self._native.rtpu_ch_write(
                self._base_addr, self.num_readers, payload, len(payload),
                flag, timeout_us,
            )
            if rc == -1:
                raise TimeoutError("channel write blocked: readers lagging")
            return
        seq = self._get(0)
        # Backpressure: previous message must be acked by every reader slot.
        deadline = None if timeout is None else time.monotonic() + timeout
        while seq > 0 and self._min_ack() < seq:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write blocked: readers lagging")
            time.sleep(0.0005)
        self._shm.buf[self._header : self._header + len(payload)] = payload
        self._set(8, len(payload))
        self._set(16, flag)
        self._set(0, seq + 1)  # publish

    def _relocate(self, needed: int, timeout: Optional[float]):
        """Grow-on-demand: allocate a larger segment, forward every reader to
        it via a RELOCATE message through the old one, then retire the old
        segment. Called with the writer role only (single writer per edge).
        Readers must all ack the relocation before the writer switches —
        afterwards both sides restart the seqlock at seq 0 in the new
        segment, so ordering is preserved without any cross-segment state."""
        old_cap = len(self._shm.buf) - self._header
        # 1.25x headroom so a steady stream of same-sized payloads relocates
        # once, not per message as pickle overhead fluctuates.
        new_cap = max(needed + needed // 4, 2 * old_cap)
        new_shm = shared_memory.SharedMemory(
            create=True, size=self._header + new_cap
        )
        new_shm.buf[: self._header] = b"\0" * self._header
        try:
            self._write_payload(pickle.dumps(new_shm.name), _FLAG_RELOC, timeout)
            # Every reader slot must observe the forward pointer before the
            # old segment is retired (their ack lands in the OLD header).
            seq = self._get(0)
            deadline = None if timeout is None else time.monotonic() + timeout
            while self._min_ack() < seq:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "channel relocate blocked: readers lagging"
                    )
                time.sleep(0.0005)
        except BaseException:
            new_shm.close()
            try:
                new_shm.unlink()
            except OSError:
                pass
            raise
        old, was_owner = self._shm, self._owner
        # The old MAPPING is retired, never closed here: sibling views in
        # this process (other reader slots, the driver's teardown handle)
        # share the SharedMemory object, and native reads may be mid-flight
        # against its address. Unlinking by name is safe while mapped; the
        # pages free when the last attachment closes (destroy/exit).
        # Retained mappings are bounded by the geometric growth (< 2x the
        # final size across all relocations).
        self._retired_shms().append(old)
        self._shm = new_shm
        self._owner = True  # this process created the grown segment
        self._bind_native()
        if was_owner:
            try:
                old.unlink()
            except OSError:
                pass
        # else: the creating process's destroy()/resource tracker unlinks it.

    def _follow_relocation(self):
        """Reader side of _relocate: attach the new segment named in the
        RELOCATE payload, ack in the old one (releasing the writer), and
        restart this reader's sequence counter for the fresh header."""
        length = self._get(8)
        new_name = pickle.loads(
            self._shm.buf[self._header : self._header + length]
        )
        self._ack()
        old, was_owner = self._shm, self._owner
        self._retired_shms().append(old)  # see _relocate: never close here
        self._shm = _attach_untracked(new_name)
        self._owner = False
        self._last_read_seq = 0
        self._bind_native()
        if was_owner:
            # The reader created the original segment (driver-made channel
            # whose writer lives in an actor): retiring it here balances the
            # creation-time tracker registration.
            try:
                old.unlink()
            except OSError:
                pass

    # --------------------------------------------------------------- read
    def begin_read(self, timeout: Optional[float] = None) -> Any:
        """Block until the next message; returns the deserialized value.
        Caller must `end_read()` when done with it. RELOCATE messages are
        consumed internally (the reader re-attaches to the grown segment and
        keeps waiting for the actual payload)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (
                None if deadline is None
                else max(deadline - time.monotonic(), 0.0)
            )
            if self._native is not None:
                import ctypes

                out_len = ctypes.c_uint64()
                out_flag = ctypes.c_uint64()
                timeout_us = -1 if remaining is None else int(remaining * 1e6)
                rc = self._native.rtpu_ch_wait_read(
                    self._base_addr, self._last_read_seq,
                    ctypes.byref(out_len), ctypes.byref(out_flag), timeout_us,
                )
                if rc == -1:
                    raise TimeoutError("channel read timed out")
                self._last_read_seq += 1
                flag, length = out_flag.value, out_len.value
            else:
                while self._get(0) <= self._last_read_seq:
                    if deadline is not None and time.monotonic() > deadline:
                        raise TimeoutError("channel read timed out")
                    time.sleep(0.0005)
                self._last_read_seq += 1
                flag, length = self._get(16), self._get(8)
            if flag == _FLAG_STOP:
                self._ack()
                raise ChannelClosed
            if flag == _FLAG_RELOC:
                self._follow_relocation()
                continue
            return pickle.loads(
                self._shm.buf[self._header : self._header + length]
            )

    def end_read(self):
        self._ack()

    def _ack(self):
        # Idempotent absolute store into this reader's own slot — safe under
        # concurrent acks from other readers.
        if self._native is not None:
            self._native.rtpu_ch_ack(
                self._base_addr, self.reader_slot, self._last_read_seq
            )
            return
        self._set(24 + 8 * self.reader_slot, self._last_read_seq)

    def read(self, timeout: Optional[float] = None) -> Any:
        """begin_read + end_read (for values that are fully copied out)."""
        value = self.begin_read(timeout)
        self.end_read()
        return value

    def _retired_shms(self) -> list:
        if not hasattr(self, "_retired"):
            self._retired = []
        return self._retired

    # ---------------------------------------------------------- lifecycle
    def close_writer(self):
        """Send the stop sentinel; readers raise ChannelClosed."""
        try:
            self._write_payload(b"", _FLAG_STOP, timeout=5.0)
        except (TimeoutError, ValueError):
            pass

    def destroy(self):
        for shm in self._retired_shms():
            try:
                shm.close()
            except Exception:  # noqa: BLE001
                pass
        self._retired = []
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # noqa: BLE001
            pass

    def __reduce__(self):
        # Re-attach on the other side. Readers inherit seq 0, so ship
        # channels BEFORE the first write (compiled DAGs do).
        return (
            _attach_channel,
            (self.name, self.num_readers, self.reader_slot, self.growable),
        )


def _attach_channel(
    name: str, num_readers: int, reader_slot: int, growable: bool = True
) -> "Channel":
    return Channel(
        name=name, create=False, num_readers=num_readers,
        reader_slot=reader_slot, growable=growable,
    )


class RemoteShmChannel:
    """Driver-side DESCRIPTOR for a shm channel that lives on another host
    (both endpoints of the edge are there; the driver never touches the
    bytes). Holds no mapping — it exists to be pickled into stage arg plans,
    where it unpickles as a real attached `Channel`. The segment itself is
    created by the producer actor (`_StageHost.create_shm_channel`) and
    unlinked by that process's resource tracker at exit."""

    def __init__(self, name: str, num_readers: int, reader_slot: int = 0):
        self.name = name
        self.num_readers = num_readers
        self.reader_slot = reader_slot

    def with_reader_slot(self, slot: int) -> "RemoteShmChannel":
        if not 0 <= slot < self.num_readers:
            raise ValueError(f"reader slot {slot} out of range [0, {self.num_readers})")
        return RemoteShmChannel(self.name, self.num_readers, slot)

    def close_writer(self):
        pass  # stop sentinels for remote-interior edges ride actor teardown

    def destroy(self):
        pass  # owning process's resource tracker unlinks at exit

    def __reduce__(self):
        return (_attach_channel, (self.name, self.num_readers, self.reader_slot))


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    from multiprocessing import resource_tracker

    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig
