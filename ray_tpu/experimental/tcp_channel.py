"""Cross-host channels for compiled DAGs.

Reference analog: `python/ray/experimental/channel.py:49` — the reference
hides the transport behind one `write`/`begin_read`/`end_read` surface so a
compiled DAG can pipeline stages whether its actors share a machine or not.
Here the cross-node transport is a persistent TCP stream per DAG edge
(single writer, N readers, depth-1 backpressure — identical semantics to the
shm seqlock `Channel`), so steady-state execution still does zero task
submissions and zero connection setups.

Roles are positional, not typed: the producer process calls
`TcpChannel.bind(...)` once (registering a listening socket in a
process-local table), and any `TcpChannel` descriptor that lands in that
process afterwards resolves to the writer end by name; descriptors landing
anywhere else are reader ends that lazily connect on first `begin_read`.
This lets the driver create every edge descriptor centrally at compile time
and ship the same object to both sides, exactly like the shm channels.

Wire protocol per message: `<QQQ>` header (seq, flag, byte-length) then the
pickled payload. Each reader acks with `<Q>` (its last fully-consumed seq)
after `end_read`; the writer blocks publishing seq S until every reader has
acked S-1 — buffer-reuse backpressure without shared memory.
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .channel import ChannelClosed

_HDR = struct.Struct("<QQQ")
_ACK = struct.Struct("<Q")
_FLAG_STOP = 1

# Process-local registry: channel name -> _WriterState. Populated by
# TcpChannel.bind(); consulted by TcpChannel.write() to resolve the writer
# role (plasma-fd-passing analog: whoever holds the bound socket is the
# producer).
_BOUND: Dict[str, "_WriterState"] = {}


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("tcp channel peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


class _WriterState:
    """Server side of one edge: listening socket + per-slot connections +
    ack bookkeeping. Lives in the producer process only."""

    def __init__(self, name: str, num_readers: int, bind_host: str):
        self.name = name
        self.num_readers = num_readers
        self.server = socket.create_server((bind_host, 0))
        self.port = self.server.getsockname()[1]
        self.conns: Dict[int, socket.socket] = {}
        self.acks = [0] * num_readers
        self.seq = 0
        self.cond = threading.Condition()
        self.closed = False
        t = threading.Thread(
            target=self._accept_loop, name=f"tcpch-accept-{name}", daemon=True
        )
        t.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return  # server closed (destroy)
            try:
                (slot,) = _ACK.unpack(_recv_exact(conn, 8))
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except Exception:  # noqa: BLE001 — malformed hello
                conn.close()
                continue
            with self.cond:
                if self.closed:
                    # Writer already closed: the late connector still gets a
                    # clean stop sentinel, not a hangup.
                    try:
                        conn.sendall(_HDR.pack(self.seq + 1, _FLAG_STOP, 0))
                    except OSError:
                        pass
                    conn.close()
                    continue
                if not 0 <= slot < self.num_readers:
                    conn.close()
                    continue
                old = self.conns.get(slot)
                if old is not None:
                    old.close()
                self.conns[slot] = conn
                self.cond.notify_all()

    def _drain_acks(self, deadline: Optional[float]):
        """Block until every reader has acked the previous message."""
        while min(self.acks) < self.seq:
            with self.cond:
                socks = {c: s for s, c in self.conns.items()}
            wait = 0.2
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    raise TimeoutError("tcp channel write blocked: readers lagging")
            readable, _, _ = select.select(list(socks), [], [], max(wait, 0.001))
            for conn in readable:
                try:
                    (acked,) = _ACK.unpack(_recv_exact(conn, 8))
                except (ConnectionError, OSError) as e:
                    raise ConnectionError(
                        f"tcp channel {self.name}: reader {socks[conn]} died"
                    ) from e
                slot = socks[conn]
                self.acks[slot] = max(self.acks[slot], acked)

    def write_payload(self, payload: bytes, flag: int, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            while len(self.conns) < self.num_readers:
                wait = 1.0 if deadline is None else deadline - time.monotonic()
                if wait <= 0 or not self.cond.wait(timeout=min(wait, 1.0)):
                    if deadline is not None and time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"tcp channel {self.name}: "
                            f"{len(self.conns)}/{self.num_readers} readers connected"
                        )
        self._drain_acks(deadline)
        self.seq += 1
        msg = _HDR.pack(self.seq, flag, len(payload)) + payload
        with self.cond:
            conns = list(self.conns.values())
        for conn in conns:
            conn.sendall(msg)

    def send_stop(self):
        """Best-effort stop sentinel to every *connected* reader (readers
        that never connected are covered by teardown closing the server)."""
        with self.cond:
            self.closed = True
            conns = list(self.conns.values())
        msg = _HDR.pack(self.seq + 1, _FLAG_STOP, 0)
        for conn in conns:
            try:
                conn.sendall(msg)
            except OSError:
                pass

    def destroy(self):
        with self.cond:
            self.closed = True
            conns = list(self.conns.values())
            self.conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        try:
            self.server.close()
        except OSError:
            pass


class TcpChannel:
    """Same surface as `channel.Channel`, TCP transport. Construct reader
    descriptors directly; construct the writer end via `TcpChannel.bind`."""

    def __init__(
        self,
        name: str,
        addr: Tuple[str, int],
        num_readers: int = 1,
        reader_slot: int = 0,
    ):
        self.name = name
        self.addr = tuple(addr)
        self.num_readers = num_readers
        self.reader_slot = reader_slot
        self._sock: Optional[socket.socket] = None
        self._last_read_seq = 0
        # Resumable-read state: bytes already received of the in-progress
        # header/payload, kept across a TimeoutError so a retried
        # begin_read (CompiledDAGRef.get's health-poll slices, or a caller
        # retrying a timed-out get) CONTINUES the stream instead of parsing
        # mid-payload bytes as a fresh header and desyncing the channel.
        self._rxbuf = bytearray()
        self._rxhdr: Optional[Tuple[int, int, int]] = None

    # ------------------------------------------------------------- writer
    @classmethod
    def bind(
        cls,
        name: str,
        num_readers: int,
        *,
        advertise_host: str,
        bind_host: str = "0.0.0.0",
    ) -> "TcpChannel":
        if name in _BOUND:
            raise ValueError(f"tcp channel {name!r} already bound in this process")
        ws = _WriterState(name, num_readers, bind_host)
        _BOUND[name] = ws
        return cls(name, (advertise_host, ws.port), num_readers)

    def _writer(self) -> _WriterState:
        ws = _BOUND.get(self.name)
        if ws is None:
            raise RuntimeError(
                f"tcp channel {self.name}: write() from a process that never "
                "bound it (reader ends are read-only)"
            )
        return ws

    def write(self, value: Any, timeout: Optional[float] = 60.0):
        self._writer().write_payload(pickle.dumps(value), 0, timeout)

    def close_writer(self):
        ws = _BOUND.get(self.name)
        if ws is not None:
            ws.send_stop()

    # ------------------------------------------------------------- reader
    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(self.addr, timeout=30.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(_ACK.pack(self.reader_slot))
            sock.settimeout(None)
            self._sock = sock
        return self._sock

    def _fill(self, sock: socket.socket, need: int, deadline: Optional[float]):
        """Append to _rxbuf until it holds `need` bytes; on timeout the
        partial bytes are KEPT for the next attempt."""
        while len(self._rxbuf) < need:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("tcp channel read timed out")
                sock.settimeout(remaining)
            try:
                b = sock.recv(min(1 << 20, need - len(self._rxbuf)))
            except socket.timeout as e:
                raise TimeoutError("tcp channel read timed out") from e
            if not b:
                raise ConnectionError("tcp channel peer closed")
            self._rxbuf.extend(b)

    def begin_read(self, timeout: Optional[float] = None) -> Any:
        sock = self._connect()
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            if self._rxhdr is None:
                self._fill(sock, _HDR.size, deadline)
                self._rxhdr = _HDR.unpack(bytes(self._rxbuf[: _HDR.size]))
                del self._rxbuf[: _HDR.size]
            seq, flag, length = self._rxhdr
            if flag == _FLAG_STOP:
                self._last_read_seq = seq
                self._rxhdr = None
                self.end_read()
                raise ChannelClosed
            self._fill(sock, length, deadline)
            payload = bytes(self._rxbuf[:length])
            del self._rxbuf[:length]
            # Acked state advances only once the message is fully consumed
            # — a timeout mid-payload must not let end_read() ack it.
            self._last_read_seq = seq
            self._rxhdr = None
        finally:
            sock.settimeout(None)
        return pickle.loads(payload)

    def end_read(self):
        if self._sock is not None:
            try:
                self._sock.sendall(_ACK.pack(self._last_read_seq))
            except OSError:
                pass

    def read(self, timeout: Optional[float] = None) -> Any:
        value = self.begin_read(timeout)
        self.end_read()
        return value

    # ---------------------------------------------------------- lifecycle
    def with_reader_slot(self, slot: int) -> "TcpChannel":
        if not 0 <= slot < self.num_readers:
            raise ValueError(f"reader slot {slot} out of range [0, {self.num_readers})")
        return TcpChannel(self.name, self.addr, self.num_readers, slot)

    def destroy(self):
        ws = _BOUND.pop(self.name, None)
        if ws is not None:
            ws.destroy()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __reduce__(self):
        return (TcpChannel, (self.name, self.addr, self.num_readers, self.reader_slot))
