"""Content-addressed packaging of working_dir / py_modules.

Reference analog: `python/ray/_private/runtime_env/packaging.py` — local
directories are zipped under a content hash (`pkg-<sha>.zip`), shipped via
GCS there / the shared session package root here, and unpacked once per node
into a cache keyed by the same hash.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import zipfile
from typing import Iterable, Optional

DEFAULT_EXCLUDES = ("__pycache__", ".git", ".venv", "*.pyc")
MAX_PACKAGE_BYTES = 512 * 1024 * 1024


def _excluded(name: str, excludes: Iterable[str]) -> bool:
    import fnmatch

    return any(fnmatch.fnmatch(name, pat) for pat in excludes)


def _walk_files(root: str, excludes: Iterable[str]):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if not _excluded(d, excludes))
        for fn in sorted(filenames):
            if not _excluded(fn, excludes):
                yield os.path.join(dirpath, fn)


def hash_directory(path: str, excludes: Iterable[str] = DEFAULT_EXCLUDES) -> str:
    """Stable content hash over relative paths + file bytes."""
    h = hashlib.sha256()
    for fp in _walk_files(path, excludes):
        rel = os.path.relpath(fp, path)
        h.update(rel.encode())
        with open(fp, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()[:16]


# (path, excludes, pkg_root) -> (mtime signature, zip path). Submitting the
# same runtime_env in a loop must not re-read the whole directory per task —
# the cheap stat-based signature detects changes; content bytes are only
# re-hashed when it moves.
_PKG_CACHE: dict = {}


def _stat_signature(path: str, excludes: Iterable[str]) -> tuple:
    sig = []
    for fp in _walk_files(path, excludes):
        st = os.stat(fp)
        sig.append((os.path.relpath(fp, path), st.st_size, st.st_mtime_ns))
    return tuple(sig)


def package_directory(
    path: str,
    pkg_root: str,
    excludes: Optional[Iterable[str]] = None,
) -> str:
    """Zip `path` into `<pkg_root>/pkg-<hash>.zip` (idempotent); returns the
    zip path. Raises on oversized packages (reference has the same guard)."""
    excludes = tuple(excludes or DEFAULT_EXCLUDES)
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory does not exist: {path}")
    os.makedirs(pkg_root, exist_ok=True)
    cache_key = (path, excludes, pkg_root)
    sig = _stat_signature(path, excludes)
    cached = _PKG_CACHE.get(cache_key)
    if cached is not None and cached[0] == sig and os.path.exists(cached[1]):
        return cached[1]
    digest = hash_directory(path, excludes)
    zip_path = os.path.join(pkg_root, f"pkg-{digest}.zip")
    if os.path.exists(zip_path):
        _PKG_CACHE[cache_key] = (sig, zip_path)
        return zip_path
    import threading
    import uuid

    tmp = (
        f"{zip_path}.tmp.{os.getpid()}.{threading.get_ident()}."
        f"{uuid.uuid4().hex[:6]}"
    )
    total = 0
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
        for fp in _walk_files(path, excludes):
            total += os.path.getsize(fp)
            if total > MAX_PACKAGE_BYTES:
                zf.close()
                os.remove(tmp)
                raise ValueError(
                    f"runtime_env package for {path} exceeds "
                    f"{MAX_PACKAGE_BYTES >> 20} MiB"
                )
            zf.write(fp, os.path.relpath(fp, path))
    os.replace(tmp, zip_path)
    _PKG_CACHE[cache_key] = (sig, zip_path)
    return zip_path


def ensure_unpacked(zip_path: str, cache_root: str) -> str:
    """Unpack `pkg-<hash>.zip` into `<cache_root>/<hash>/` exactly once
    (atomic rename makes concurrent workers race-safe); returns the dir."""
    name = os.path.splitext(os.path.basename(zip_path))[0]
    target = os.path.join(cache_root, name)
    if os.path.isdir(target):
        return target
    os.makedirs(cache_root, exist_ok=True)
    tmp = f"{target}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    with zipfile.ZipFile(zip_path) as zf:
        # Zip-slip guard: every member must resolve INSIDE the target dir
        # (the package root is shared across jobs — a crafted archive must
        # not write elsewhere via absolute paths or '..' components).
        root = os.path.realpath(tmp)
        for member in zf.namelist():
            dest = os.path.realpath(os.path.join(root, member))
            if dest != root and not dest.startswith(root + os.sep):
                raise ValueError(
                    f"unsafe member path {member!r} in {zip_path}"
                )
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # another worker won the race
    return target
