"""Worker-process isolation for runtime envs: conda + container.

Reference analog: `python/ray/_private/runtime_env/conda.py` and
`container.py` — these envs can't be applied inside a running worker (they
change the interpreter / the filesystem), so the RAYLET starts the worker
through a wrapper command (`conda run` / `podman run`). Same design here:
the node agent wraps the worker argv, the scheduler keys workers by
isolation hash (`isolation_key`) and only dispatches matching tasks onto
them — a task with `runtime_env={"conda": "myenv"}` never lands on a plain
pooled worker.

Zero-egress scoping: conda env CREATION from a spec dict needs an index and
is rejected; existing envs (by name or prefix) are supported. Both features
gate on the binary actually existing on the node (`conda`, and
`podman`/`docker` for containers) — absent binaries fail the worker spawn,
which surfaces as the task error, exactly like the reference's
RUNTIME_ENV_SETUP_FAILED path.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, List, Optional


def resolve(renv: Optional[dict]) -> Optional[Dict[str, Any]]:
    """runtime_env -> {"kind", "spec", "key"} or None if not isolated."""
    if not renv:
        return None
    if renv.get("conda"):
        spec = renv["conda"]
        return {"kind": "conda", "spec": spec, "key": _key("conda", spec)}
    if renv.get("container"):
        spec = renv["container"]
        return {"kind": "container", "spec": spec, "key": _key("container", spec)}
    return None


def isolation_key(renv: Optional[dict]) -> str:
    iso = resolve(renv)
    return iso["key"] if iso else ""


def _key(kind: str, spec: Any) -> str:
    blob = json.dumps(spec, sort_keys=True) if isinstance(spec, dict) else str(spec)
    return f"{kind}:{hashlib.sha256(blob.encode()).hexdigest()[:12]}"


def validate_spec(kind: str, spec: Any):
    if kind == "conda":
        if isinstance(spec, dict):
            raise ValueError(
                "runtime_env conda env CREATION from a spec dict needs a "
                "package index (zero-egress image); pass an existing env "
                "name or prefix path instead"
            )
        if not isinstance(spec, str) or not spec:
            raise ValueError("runtime_env conda must be an env name or prefix path")
    elif kind == "container":
        if not isinstance(spec, dict) or not spec.get("image"):
            raise ValueError(
                'runtime_env container must be {"image": ..., '
                '"run_options": [...]} (reference container field shape)'
            )
    else:
        raise ValueError(f"unknown isolation kind {kind!r}")


def _container_engine() -> Optional[str]:
    engine = os.environ.get("RAY_TPU_CONTAINER_ENGINE")
    if engine:
        return engine if shutil.which(engine) else None
    for candidate in ("podman", "docker"):
        if shutil.which(candidate):
            return candidate
    return None


# Env vars a containerized worker needs forwarded explicitly (`docker run`
# does not inherit the spawner's environment the way fork/exec does).
_FORWARD_PREFIXES = ("RAY_TPU_", "JAX_", "XLA_")
_FORWARD_EXACT = ("PYTHONPATH", "PYTHONUNBUFFERED", "TPU_SKIP_MDS_QUERY")


def _relocated(base_argv: List[str]) -> List[str]:
    """The wrapped command runs in a DIFFERENT interpreter world; the
    spawner's absolute `sys.executable` would escape it (`conda run` would
    exec the HOST interpreter with host site-packages; a container image
    likely has no python at that host path at all). Swap an absolute
    interpreter path for PATH-resolved `python3` (the PEP 394 guaranteed
    name; Debian-family images often ship no bare `python`), which the
    wrapper environment resolves to ITS interpreter — the entire point of the
    feature."""
    if base_argv and os.path.isabs(base_argv[0]):
        return ["python3"] + base_argv[1:]
    return list(base_argv)


def build_argv(
    isolation: Dict[str, Any], base_argv: List[str], env: Dict[str, str],
    session_dir: str,
) -> List[str]:
    """Wrap `base_argv` (the worker command) for the isolation kind.
    Raises RuntimeError when the needed binary is absent on this node."""
    kind, spec = isolation["kind"], isolation["spec"]
    validate_spec(kind, spec)
    base_argv = _relocated(base_argv)
    if kind == "conda":
        conda = os.environ.get("CONDA_EXE") or shutil.which("conda")
        if conda is None:
            raise RuntimeError(
                "runtime_env conda requested but no `conda` binary on this "
                "node (set CONDA_EXE or install conda in the node image)"
            )
        flag = "-p" if os.sep in spec else "-n"
        return [conda, "run", flag, spec, "--no-capture-output"] + base_argv

    engine = _container_engine()
    if engine is None:
        raise RuntimeError(
            "runtime_env container requested but neither podman nor docker "
            "is on this node (set RAY_TPU_CONTAINER_ENGINE to override)"
        )
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    argv = [
        engine, "run", "--rm",
        # The worker must reach the controller (TCP), the node arena
        # (/dev/shm), and the session dir — same trust domain as the host
        # worker, different filesystem (the point of the feature).
        "--network=host", "--ipc=host",
        "-v", f"{session_dir}:{session_dir}",
        "-v", f"{pkg_root}:{pkg_root}:ro",
    ]
    for k, v in env.items():
        if k.startswith(_FORWARD_PREFIXES) or k in _FORWARD_EXACT:
            argv += ["-e", f"{k}={v}"]
    argv += list(spec.get("run_options", []))
    argv += [spec["image"]] + base_argv
    return argv
