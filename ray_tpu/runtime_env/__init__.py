"""Runtime environments — per-task/actor execution environments.

Reference analog: `python/ray/runtime_env/` (user API) +
`python/ray/_private/runtime_env/` (plugins: `working_dir.py`,
`py_modules.py`, `pip.py`, `conda.py`, plugin system `plugin.py`, served by
the per-node agent `agent/runtime_env_agent.py:161`).

Redesign (TPU-first, zero-egress aware):
  * `env_vars` — applied around task execution in the worker (persists for
    an actor's lifetime).
  * `working_dir` / `py_modules` — local dirs are content-hash packaged at
    submission into the session package root and unpacked once per worker
    node cache; applied as cwd / sys.path mutations around execution.
  * `pip` — requirement availability is VERIFIED against the worker's
    interpreter (distribution metadata first, import fallback); missing
    requirements raise `RuntimeEnvSetupError` exactly like the reference's
    failed env setup. RAY_TPU_RUNTIME_ENV_ALLOW_PIP=1 additionally installs
    missing requirements into the (shared, non-isolated) worker interpreter
    — a bootstrap escape hatch for images with an index, not per-task
    isolation.
  * `conda` / `container` — WORKER-LEVEL isolation (these can't be applied
    inside a running interpreter): the scheduler keys workers by isolation
    hash and the node agent spawns them through `conda run` / `podman run`
    (see `isolation.py`; reference: `_private/runtime_env/conda.py`,
    `container.py`). Gated on the binary existing on the node; conda env
    CREATION from spec dicts stays rejected (zero-egress image).
  * custom plugins — `register_plugin(name, plugin)` with driver-side
    `prepare` and worker-side `apply` hooks.
"""

from __future__ import annotations

import importlib
import os
import re
import sys
from typing import Any, Callable, Dict, List, Optional

from .packaging import ensure_unpacked, package_directory

KNOWN_FIELDS = {
    "env_vars",
    "working_dir",
    "py_modules",
    "pip",
    "conda",
    "container",
    "config",
    # Internal (driver-prepared) fields:
    "_working_dir_pkg",
    "_py_module_pkgs",
}


class RuntimeEnvSetupError(RuntimeError):
    """Environment could not be set up on the worker (reference:
    `ray.exceptions.RuntimeEnvSetupError`)."""


class RuntimeEnvPlugin:
    """Custom plugin seam (reference: `_private/runtime_env/plugin.py`).

    `prepare` runs on the driver at submission (package/validate);
    `apply` runs on the worker around execution and returns a restore
    callable (or None)."""

    def prepare(self, value: Any, session_dir: str) -> Any:
        return value

    def apply(self, value: Any, session_dir: str) -> Optional[Callable[[], None]]:
        return None


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(name: str, plugin: RuntimeEnvPlugin):
    if name in KNOWN_FIELDS:
        raise ValueError(f"'{name}' is a builtin runtime_env field")
    _PLUGINS[name] = plugin


class RuntimeEnv(dict):
    """Validated runtime_env mapping (reference:
    `python/ray/runtime_env/runtime_env.py`)."""

    def __init__(self, **kwargs):
        validate(kwargs)
        super().__init__(**kwargs)


def validate(renv: dict):
    for key, value in renv.items():
        if key not in KNOWN_FIELDS and key not in _PLUGINS:
            raise ValueError(
                f"Unknown runtime_env field '{key}' "
                f"(known: {sorted(KNOWN_FIELDS - {'_working_dir_pkg', '_py_module_pkgs'})}, "
                f"plugins: {sorted(_PLUGINS)})"
            )
        if key == "env_vars":
            if not isinstance(value, dict) or not all(
                isinstance(k, str) for k in value
            ):
                raise ValueError("runtime_env env_vars must be a str-keyed dict")
        if key == "working_dir" and not isinstance(value, str):
            raise ValueError("runtime_env working_dir must be a directory path")
        if key == "py_modules" and not isinstance(value, (list, tuple)):
            raise ValueError("runtime_env py_modules must be a list of paths")
        if key == "pip":
            if isinstance(value, dict):
                value = value.get("packages", [])
            if not isinstance(value, (list, tuple)):
                raise ValueError("runtime_env pip must be a list of requirements")
        if key in ("conda", "container"):
            from .isolation import validate_spec

            validate_spec(key, value)


# ------------------------------------------------------------- driver side
def pkg_root_for(session_dir: str) -> str:
    return os.path.join(session_dir, "runtime_env_packages")


def prepare_runtime_env(renv: Optional[dict], session_dir: str) -> Optional[dict]:
    """Submission-time transform: package local dirs into the session package
    root so any worker (node) can unpack them. Idempotent — already-prepared
    envs pass through."""
    if not renv:
        return renv
    validate(renv)
    out = dict(renv)
    root = pkg_root_for(session_dir)
    if renv.get("working_dir") and "_working_dir_pkg" not in renv:
        out["_working_dir_pkg"] = package_directory(renv["working_dir"], root)
    if renv.get("py_modules") and "_py_module_pkgs" not in renv:
        out["_py_module_pkgs"] = [
            package_directory(p, root) for p in renv["py_modules"]
        ]
    # Custom plugins ship BY VALUE (cloudpickle) so workers need no import
    # path or registry of their own (redesign of the reference's
    # RAY_RUNTIME_ENV_PLUGINS class-path env var).
    import cloudpickle

    for name, plugin in _PLUGINS.items():
        if name in out and not (
            isinstance(out[name], dict) and "__plugin__" in out[name]
        ):
            out[name] = {
                "__plugin__": cloudpickle.dumps(plugin),
                "value": plugin.prepare(out[name], session_dir),
            }
    return out


# ------------------------------------------------------------- worker side
_REQ_SPLIT = re.compile(r"[<>=!~\[;]")


def _requirement_available(req: str) -> bool:
    name = _REQ_SPLIT.split(req)[0].strip()
    # Distribution lookup first — module names often differ from PyPI names
    # (pillow→PIL, scikit-learn→sklearn); import guess only as fallback.
    try:
        import importlib.metadata as md

        md.distribution(re.sub(r"[-_.]+", "-", name))
        return True
    except Exception:  # noqa: BLE001 — PackageNotFoundError and exotica
        pass
    try:
        importlib.import_module(name.replace("-", "_"))
        return True
    except ImportError:
        return False


def _check_pip(requirements) -> None:
    if isinstance(requirements, dict):
        requirements = requirements.get("packages", [])
    missing = [req for req in requirements if not _requirement_available(req)]
    if not missing:
        return
    if os.environ.get("RAY_TPU_RUNTIME_ENV_ALLOW_PIP") == "1":
        # Deliberately NOT isolated: installs into the worker interpreter and
        # persists for the process (a bootstrap escape hatch, not per-task
        # isolation — bake real deps into the image).
        import subprocess

        subprocess.check_call(
            [sys.executable, "-m", "pip", "install", *missing]
        )
        return
    raise RuntimeEnvSetupError(
        f"runtime_env pip requirements not available in the worker image: "
        f"{missing}. This environment has no package egress; bake the "
        "dependency into the image or set RAY_TPU_RUNTIME_ENV_ALLOW_PIP=1 "
        "where an index is reachable."
    )


def apply_runtime_env(
    renv: Optional[dict], cache_root: str
) -> Callable[[], None]:
    """Apply working_dir / py_modules / pip / plugins on the worker; returns
    a restore closure (env_vars are handled by the caller, which owns the
    process env lock)."""
    if not renv:
        return lambda: None
    restores: List[Callable[[], None]] = []
    try:
        if renv.get("pip"):
            _check_pip(renv["pip"])
        if renv.get("_py_module_pkgs"):
            added = []
            for pkg in renv["_py_module_pkgs"]:
                d = ensure_unpacked(pkg, cache_root)
                sys.path.insert(0, d)
                added.append(d)

            def _pop_modules(added=added):
                for d in added:
                    try:
                        sys.path.remove(d)
                    except ValueError:
                        pass

            restores.append(_pop_modules)
        if renv.get("_working_dir_pkg"):
            d = ensure_unpacked(renv["_working_dir_pkg"], cache_root)
            old_cwd = os.getcwd()
            os.chdir(d)
            sys.path.insert(0, d)

            def _restore_cwd(d=d, old_cwd=old_cwd):
                try:
                    sys.path.remove(d)
                except ValueError:
                    pass
                try:
                    os.chdir(old_cwd)
                except OSError:
                    pass

            restores.append(_restore_cwd)
        elif renv.get("working_dir"):
            # Unpackaged path (e.g. local_mode or same-host job): use as-is.
            old_cwd = os.getcwd()
            os.chdir(renv["working_dir"])

            def _restore_plain(old_cwd=old_cwd):
                try:
                    os.chdir(old_cwd)
                except OSError:
                    pass

            restores.append(_restore_plain)
        for name, value in renv.items():
            if isinstance(value, dict) and "__plugin__" in value:
                import cloudpickle

                plugin = cloudpickle.loads(value["__plugin__"])
                r = plugin.apply(value["value"], cache_root)
                if r is not None:
                    restores.append(r)
    except BaseException:
        for r in reversed(restores):
            r()
        raise

    def restore_all():
        for r in reversed(restores):
            r()

    return restore_all
