"""Paged KV-cache block manager with automatic prefix caching
(reference-era analog: vLLM's BlockManager + its hash-based prefix cache,
`vllm/core/block_manager.py` — the PagedAttention half of iteration-level
scheduling).

The physical KV cache is a fixed pool of `num_blocks` blocks of
`block_size` token slots each (the engine owns the actual [L, NB, H, BS, Dh]
arrays; this class owns only the *map*). Each live sequence holds an ordered
block table — logical token position `p` lives in physical block
`table[p // block_size]` at offset `p % block_size`.

Prefix caching: every FULL block whose KV has been computed is registered
under a content hash CHAINED over token ids (block i's key commits to every
token in blocks 0..i, so two sequences share a block only when their entire
prefixes match). Blocks are refcounted; `allocate_cached` walks a new
prompt's chain through the hash index and reuses every leading hit — the
prefill skips straight to the first cold block. Freed blocks whose content
is registered are RETAINED on an LRU "cached" list instead of being blanked:
they serve future hits, yet remain reclaimable — the free list exhausting
falls back to evicting the coldest cached block. Admission math
(`can_allocate` / `free_blocks`) therefore counts blank + cached blocks;
`KVStats.utilization` counts only live (referenced) blocks.

Tiered cache (the cluster-wide half, PR 12): with a `host_tier`
(`kv_tier.HostKVTier`) attached, an HBM eviction SAVES the block's bytes to
host RAM instead of killing the content — the manager queues (hash, block)
save orders the engine drains (`drain_saves`) before the block is
overwritten, and `allocate_cached` consults the tier on an index miss:
a tier hit acquires a fresh block, re-registers the hash, and queues a
(hash, block, bytes, remote) LOAD (`drain_loads`) the engine applies to
the HBM arrays before its next kernel launch. `adopt_block` is the same mechanism
driven by a REMOTE import (`engine.import_blocks`): blocks computed by a
prefill-pool replica land here as cached entries. The manager stays a pure
map — every byte move is drained by the engine at a step boundary, ordered
saves -> COW -> loads -> kernels so evicted bytes are read before anything
overwrites them. Hot-hash digest entries survive HBM eviction while the
bytes remain host-resident (the fleet router keeps steering matching
prompts here, where the import is a host-RAM copy, not a recompute).

Invariants (enforced by `check_invariants`):
  * every block is blank (free list) XOR cached (ref 0, content retained)
    XOR live (ref >= 1) — never two at once, none lost;
  * a block's refcount equals its number of table references;
  * a refcounted-shared block is NEVER written in place: extending a
    sequence into a shared block forks it copy-on-write — the manager
    rewrites the table and queues a (src, dst) physical copy for the engine
    (`drain_cow`); only full, immutable blocks are ever hash-shared.

Admission control rides on `can_allocate`: the scheduler refuses (queues,
never crashes) a prefill whose prompt + first token doesn't fit
blank + reclaimable blocks, and preempts the youngest running sequence when
decode growth hits the budget mid-flight.

Block 0 is RESERVED as the null/scratch block: the engine pads decode
batches to bucket shapes by pointing dummy lanes' block tables at block 0,
so their writes land somewhere harmless. It is never handed out.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple


class KVCacheExhausted(RuntimeError):
    """Raised by allocate/grow when blank + evictable blocks cannot cover
    the request.

    The scheduler treats this as back-pressure (requeue/preempt), never as a
    crash — it reaches user code only on programming errors (e.g. a prompt
    longer than the whole pool, which `fits_ever` screens at submit)."""


def _chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    """Content key of one full block given its predecessor's key — collision
    resistance matters (a collision would silently serve another prompt's
    KV), so this is a real hash, not Python's."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(b",".join(str(int(t)).encode() for t in tokens))
    return h.digest()


# Wire width of one hot-prefix digest entry (`prefix_digest`): the fleet
# router only needs to DISCRIMINATE prefixes (a truncation collision routes
# to a replica that turns out to miss — no correctness impact), so digests
# ship 8 of the 16 hash bytes. `serve/fleet/routing.py` derives its routing
# keys with the same truncation.
DIGEST_HASH_BYTES = 8

# Hot-prefix hashes retained for digest export (recency-ordered).
_HOT_CAP = 512


@dataclasses.dataclass(frozen=True)
class KVStats:
    num_blocks: int          # allocatable blocks (excludes the null block)
    free_blocks: int         # allocatable NOW: blank + reclaimable cached
    used_blocks: int         # referenced by >= 1 live sequence
    cached_blocks: int       # ref == 0 but content retained (subset of free)
    num_seqs: int
    utilization: float       # LIVE fraction of the pool, 0..1
    hits: int = 0            # full blocks reused from the prefix cache
    misses: int = 0          # cacheable full blocks that had to be computed
    evictions: int = 0       # cached blocks reclaimed for new allocations
    cow_copies: int = 0      # copy-on-write forks of shared blocks
    host_hits: int = 0       # hits served from the host-RAM tier (subset)
    host_blocks: int = 0     # blocks resident in the host tier
    host_bytes: int = 0      # bytes resident in the host tier


class KVBlockManager:
    """Refcounting free-list allocator mapping sequence ids to ordered block
    tables, with a chained-hash prefix cache over full blocks."""

    NULL_BLOCK = 0

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        host_tier=None,
    ):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.caching = enable_prefix_caching
        # Host-RAM tier below HBM (kv_tier.HostKVTier, None = off). Accessed
        # only under the engine lock, like every other mutation here.
        self._tier = host_tier if enable_prefix_caching else None
        if self._tier is not None:
            self._tier.on_evict = self._on_tier_evict
        # Block 0 reserved; LIFO free list so recently-freed (cache-warm)
        # blocks are reused first.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        # ref == 0 blocks whose content is still registered: insertion order
        # is recency (oldest first = LRU eviction order).
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        self._ref: Dict[int, int] = {}            # live blocks only
        self._tables: Dict[str, List[int]] = {}
        self._lens: Dict[str, int] = {}           # tokens stored per sequence
        self._hash_of: Dict[int, bytes] = {}      # registered block -> key
        self._index: Dict[bytes, int] = {}        # key -> canonical block
        self._chain: Dict[str, List[bytes]] = {}  # per-seq registered keys
        # Recency-ordered registered/hit hashes (hottest LAST): the bounded
        # hot-prefix digest the fleet router steers by. Advisory only —
        # entries die with their index entry on eviction.
        self._hot: "OrderedDict[bytes, None]" = OrderedDict()
        # (src, dst) physical copies the ENGINE must apply before the next
        # kernel launch — the manager owns only the map.
        self._pending_copies: List[Tuple[int, int]] = []
        # block -> (hash, bytes, remote): tier/import content the engine
        # must land in the HBM arrays before its next kernel launch
        # (drain_loads); `remote` marks content adopted from ANOTHER
        # replica's export (adopt_block) vs a local host-tier re-admission
        # — the engine's import counter tracks only the former. An eviction
        # of a pending-load block just drops the entry — the bytes never
        # reached HBM, so there is nothing to save and the index entry dies
        # with it.
        self._pending_loads: Dict[int, Tuple[bytes, object, bool]] = {}
        # (hash, block): evicted registered blocks whose bytes the engine
        # must copy OUT to the host tier before anything overwrites them
        # (drain_saves runs FIRST in the engine's step-top drain order).
        self._pending_saves: List[Tuple[bytes, int]] = []
        # Landed watermark per sequence: tokens whose KV is KNOWN computed
        # (prefix-cache hits at admission + every register_computed
        # notification). Lags the true cursor by at most the notification
        # granularity; `fork` trims the child to it so a speculatively
        # over-allocated parent can never leak an un-COWed shared tail.
        self._landed: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.cow_copies = 0
        self.host_hits = 0

    # ------------------------------------------------------------- queries
    @property
    def free_blocks(self) -> int:
        """Blocks allocatable right now (blank + evictable cached)."""
        return len(self._free) + self._evictable()

    def _evictable(self) -> int:
        # Cached blocks that are the source of a still-pending COW copy must
        # survive until the engine applies it; they drop out of the
        # reclaimable count until drain_cow().
        if not self._pending_copies:
            return len(self._cached)
        protected = {s for s, _ in self._pending_copies}
        return sum(1 for b in self._cached if b not in protected)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)  # ceil div

    def can_allocate(self, num_tokens: int) -> bool:
        return self.blocks_for(num_tokens) <= self.free_blocks

    def fits_ever(self, num_tokens: int) -> bool:
        """Could this many tokens fit an EMPTY pool? (submit-time sanity)"""
        return self.blocks_for(num_tokens) <= self.num_blocks - 1

    def block_table(self, seq_id: str) -> List[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id: str) -> int:
        return self._lens[seq_id]

    def num_registered(self, seq_id: str) -> int:
        """Full blocks of `seq_id` already in the prefix index — the
        scheduler's cheap check for whether registration has blocks to
        catch up on (multi-token speculative appends can jump PAST a block
        boundary, so an exact `landed % block_size == 0` test misses)."""
        return len(self._chain.get(seq_id, ()))

    def _touch_hot(self, h: bytes) -> None:
        self._hot[h] = None
        self._hot.move_to_end(h)
        while len(self._hot) > _HOT_CAP:
            self._hot.popitem(last=False)

    def prefix_digest(self, max_entries: int = 64) -> List[str]:
        """Bounded digest of the HOTTEST prefix hashes (truncated hex,
        hottest first) — piggybacked on controller telemetry so fleet
        routers can steer prompts toward the replica already holding their
        prefix. Empty when prefix caching is off."""
        if not self.caching or max_entries < 1:
            return []
        out = []
        for h in reversed(self._hot):
            out.append(h[:DIGEST_HASH_BYTES].hex())
            if len(out) >= max_entries:
                break
        return out

    def stats(self) -> KVStats:
        total = self.num_blocks - 1
        live = len(self._ref)
        return KVStats(
            num_blocks=total,
            free_blocks=len(self._free) + self._evictable(),
            used_blocks=live,
            cached_blocks=len(self._cached),
            num_seqs=len(self._tables),
            utilization=live / total if total else 0.0,
            hits=self.hits,
            misses=self.misses,
            evictions=self.evictions,
            cow_copies=self.cow_copies,
            host_hits=self.host_hits,
            host_blocks=self._tier.blocks if self._tier is not None else 0,
            host_bytes=self._tier.bytes_used if self._tier is not None else 0,
        )

    # ------------------------------------------------------- block plumbing
    def _acquire(self) -> int:
        """One blank block: the free list first, then LRU-evict the coldest
        cached block. Without a host tier the evictee's index entry dies
        with it; with one, its bytes are queued to SAVE into host RAM (the
        engine drains before anything overwrites the block) and its hot-hash
        digest entry survives — the fleet router keeps steering matching
        prompts here, where `allocate_cached`'s tier consult makes the
        re-admission a host-RAM copy instead of a recompute."""
        if self._free:
            return self._free.pop()
        protected = {s for s, _ in self._pending_copies}
        for b in self._cached:
            if b not in protected:
                del self._cached[b]
                h = self._hash_of.pop(b)
                del self._index[h]
                self.evictions += 1
                pending = self._pending_loads.pop(b, None)
                if self._tier is not None and pending is None:
                    # Bytes are in HBM and about to be reused: save them to
                    # the host tier (skip when the tier already holds them).
                    if not self._tier.contains(h):
                        self._pending_saves.append((h, b))
                    # Host-resident content stays advertised (hot entry
                    # kept); the tier's own eviction drops it for real.
                elif pending is not None and self._tier is not None \
                        and self._tier.contains(h):
                    pass  # bytes still live in the tier — stay advertised
                else:
                    self._hot.pop(h, None)
                return b
        raise KVCacheExhausted("KV pool exhausted (no blank or evictable blocks)")

    def _on_tier_evict(self, h: bytes) -> None:
        """Host-tier budget eviction: the content is now gone everywhere
        below the fleet — stop advertising it (unless it is independently
        registered in HBM)."""
        if h not in self._index:
            self._hot.pop(h, None)

    def _incref(self, b: int) -> None:
        if b in self._ref:
            self._ref[b] += 1
        else:  # reviving a cached (ref 0) block
            del self._cached[b]
            self._ref[b] = 1

    def _release_one(self, b: int) -> None:
        r = self._ref[b] - 1
        if r > 0:
            self._ref[b] = r
            return
        del self._ref[b]
        if b in self._hash_of:
            # Content stays findable: most-recently-freed lands at the LRU
            # tail, so eviction takes the coldest prefix first.
            self._cached[b] = None
        else:
            assert b != self.NULL_BLOCK and b not in self._free, (
                f"block {b} double-freed"
            )
            self._free.append(b)

    # --------------------------------------------------------- allocation
    def allocate(self, seq_id: str, num_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of `num_tokens` tokens, with no
        cache lookup (token ids unknown). Raises KVCacheExhausted when
        blank + evictable blocks can't cover it (the caller keeps the
        request queued) and ValueError on reuse of a live seq_id."""
        table, _ = self.allocate_cached(seq_id, None, num_tokens)
        return table

    def allocate_cached(
        self,
        seq_id: str,
        token_ids: Optional[Sequence[int]],
        num_tokens: int,
    ) -> Tuple[List[int], int]:
        """Claim blocks for a new sequence, reusing every leading full block
        whose chained content hash is already registered.

        `token_ids` is the prompt (length <= num_tokens; the surplus covers
        generated tokens). Returns (block_table, cached_tokens):
        `cached_tokens` prompt positions already hold valid KV — the prefill
        starts at that offset. At least one prompt token is always left cold
        so the engine has a real position to read next-token logits from.
        """
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has an allocation")
        if num_tokens < 1:
            raise ValueError("allocate needs >= 1 token")
        if token_ids is not None and len(token_ids) > num_tokens:
            raise ValueError("token_ids longer than the allocation")
        need_total = self.blocks_for(num_tokens)
        # Chain walk: per leading full block, an HBM index hit ("idx", b),
        # a host-tier hit ("tier", h, bytes) — acquired below and loaded by
        # the engine before its next kernel — or a miss (walk ends).
        walk: List[Tuple] = []
        chain: List[bytes] = []
        if self.caching and token_ids is not None and len(token_ids) > 1:
            # Cap: never serve the WHOLE prompt from cache — the last
            # position must be recomputed to produce first-token logits.
            cacheable = (len(token_ids) - 1) // self.block_size
            prev = b""
            for i in range(cacheable):
                h = _chain_hash(
                    prev,
                    token_ids[i * self.block_size:(i + 1) * self.block_size],
                )
                b = self._index.get(h)
                if b is not None:
                    walk.append(("idx", b))
                elif self._tier is not None:
                    blob = self._tier.get(h)  # touches the tier's LRU
                    if blob is None:
                        break
                    walk.append(("tier", h, blob))
                else:
                    break
                chain.append(h)
                self._touch_hot(h)
                prev = h
            self.hits += len(walk)
            self.host_hits += sum(1 for w in walk if w[0] == "tier")
            self.misses += cacheable - len(walk)
        idx_hits = [w[1] for w in walk if w[0] == "idx"]
        # Hits currently resting on the cached list are about to be revived —
        # they can't double as eviction fodder for our own fresh blocks
        # (COW-protected ones were never counted evictable to begin with).
        # Tier hits and cold blocks both need a real acquisition.
        protected = {s for s, _ in self._pending_copies}
        reviving = sum(
            1 for b in idx_hits
            if b not in self._ref and b not in protected
        )
        need_new = need_total - len(idx_hits)
        if need_new > len(self._free) + self._evictable() - reviving:
            raise KVCacheExhausted(
                f"{need_new} blocks needed, "
                f"{len(self._free) + self._evictable() - reviving} available"
            )
        # Revive/share EVERY index hit first: a tier-hit acquisition below
        # may evict from the cached list, and a hit resting there must not
        # be its victim.
        for w in walk:
            if w[0] == "idx":
                self._incref(w[1])
        table: List[int] = []
        for w in walk:
            if w[0] == "idx":
                table.append(w[1])
            else:
                _, h, blob = w
                nb = self._acquire()
                self._ref[nb] = 1
                self._index[h] = nb
                self._hash_of[nb] = h
                self._pending_loads[nb] = (h, blob, False)
                table.append(nb)
        for _ in range(need_total - len(walk)):
            nb = self._acquire()
            self._ref[nb] = 1
            table.append(nb)
        self._tables[seq_id] = table
        self._lens[seq_id] = num_tokens
        self._chain[seq_id] = chain
        self._landed[seq_id] = len(walk) * self.block_size
        return list(table), len(walk) * self.block_size

    def fork(self, parent_id: str, child_id: str) -> List[int]:
        """Share `parent_id`'s table up to its LANDED watermark with a new
        sequence (beam / n-best style). Shared blocks incref; whichever
        sequence later extends into a shared partial block triggers
        copy-on-write there.

        The child is TRIMMED to the parent's landed watermark (tokens whose
        KV is known computed: admission cache hits + every
        `register_computed` notification): a parent carrying a SPECULATIVE
        over-allocation (`_lens` grown past the landed watermark to fund
        drafts the verify step may reject) must not hand the child slots
        whose content is undefined — grow()'s COW check keys off `_lens`,
        so an un-trimmed child writing below the over-allocated tail would
        miss its copy (the PR 7 caveat, now handled instead of documented).
        The watermark lags true compute by at most the notification
        granularity; the trimmed tail is re-derivable (the child recomputes
        or re-hits it). A parent allocated via plain `allocate()` (token
        ids unknown) that was never advanced by `grow(..., num_computed=)`
        or `register_computed` has watermark 0 and shares NOTHING — the
        manager cannot tell its content from speculative garbage."""
        if child_id in self._tables:
            raise ValueError(f"sequence {child_id!r} already has an allocation")
        table = self._tables[parent_id]  # KeyError = unknown parent
        landed = self._landed.get(parent_id, 0)
        keep = min(self.blocks_for(landed), len(table))
        shared = table[:keep]
        for b in shared:
            self._incref(b)
        self._tables[child_id] = list(shared)
        self._lens[child_id] = min(landed, self._lens[parent_id])
        chain = self._chain.get(parent_id, ())
        self._chain[child_id] = list(chain[:keep])
        self._landed[child_id] = self._lens[child_id]
        return list(shared)

    def grow(
        self,
        seq_id: str,
        new_len: int,
        token_ids: Optional[Sequence[int]] = None,
        num_computed: Optional[int] = None,
    ) -> List[int]:
        """Extend `seq_id`'s table to cover `new_len` tokens (decode append).

        If the next write position falls inside a SHARED block (fork), that
        block is forked copy-on-write first: the table is rewritten and a
        (src, dst) physical copy is queued for `drain_cow`. With `token_ids`
        (the sequence's full token list) and `num_computed` (tokens whose KV
        is actually written), newly-completed full blocks are registered in
        the prefix index. Returns the (possibly extended) block table;
        KVCacheExhausted when the pool is dry — the scheduler preempts.

        `new_len` below the current coverage is a no-op on the table
        (registration still runs): a speculative grow funds draft slots the
        verify step may reject, so the NEXT step legitimately asks for less
        than the table already covers."""
        table = self._tables[seq_id]
        cur = self._lens[seq_id]
        if new_len < cur:
            new_len = cur
        need = self.blocks_for(new_len) - len(table)
        wi = cur // self.block_size      # block the next write lands in
        need_cow = int(
            wi < len(table) and self._ref[table[wi]] > 1
        )
        if need + need_cow > len(self._free) + self._evictable():
            raise KVCacheExhausted(
                f"{need + need_cow} blocks needed, "
                f"{len(self._free) + self._evictable()} free"
            )
        if need_cow:
            src = table[wi]
            dst = self._acquire()
            self._ref[dst] = 1
            self._pending_copies.append((src, dst))
            table[wi] = dst
            self._release_one(src)   # still held by the other owner(s)
            self.cow_copies += 1
        for _ in range(need):
            nb = self._acquire()
            self._ref[nb] = 1
            table.append(nb)
        self._lens[seq_id] = new_len
        if token_ids is not None and num_computed is not None:
            self.register_computed(seq_id, token_ids, num_computed)
        return list(table)

    def register_computed(
        self,
        seq_id: str,
        token_ids: Sequence[int],
        num_computed: int,
    ) -> None:
        """Register every newly-FULL block whose KV is written (positions
        < `num_computed`) in the prefix index. Must only be called after the
        engine has actually landed those positions' K/V — registering ahead
        of the compute would serve garbage to the next prompt.

        If a block's key already has a canonical twin (same content computed
        by an earlier sequence), this table adopts the twin and releases its
        own copy — identical prefixes converge to identical tables."""
        landed = min(num_computed, len(token_ids))
        if landed > self._landed.get(seq_id, 0):
            self._landed[seq_id] = landed
        if not self.caching:
            return
        chain = self._chain.setdefault(seq_id, [])
        table = self._tables[seq_id]
        full = min(num_computed, len(token_ids)) // self.block_size
        while len(chain) < full:
            i = len(chain)
            prev = chain[-1] if chain else b""
            h = _chain_hash(
                prev, token_ids[i * self.block_size:(i + 1) * self.block_size]
            )
            b = table[i]
            canon = self._index.get(h)
            if canon is not None and canon != b:
                self._incref(canon)
                table[i] = canon
                self._release_one(b)
            elif canon is None:
                self._index[h] = b
                self._hash_of[b] = h
            self._touch_hot(h)
            chain.append(h)

    # ----------------------------------------------------- tier / transfer
    def holds(self, h: bytes) -> Optional[int]:
        """Physical block registered under content hash `h`, or None."""
        return self._index.get(h)

    def adopt_block(self, h: bytes, blob) -> Optional[int]:
        """Adopt externally-computed KV content (a remote replica's export,
        fetched by `engine.import_blocks`): acquire a block, register it
        under `h`, park it on the cached LRU (MRU end), and queue the bytes
        as a pending LOAD the engine lands before its next kernel. Returns
        the block, or None when the pool has nothing to give (the import
        degrades to recompute — never an error)."""
        if not self.caching or h in self._index:
            return None
        try:
            b = self._acquire()
        except KVCacheExhausted:
            return None
        self._index[h] = b
        self._hash_of[b] = h
        self._cached[b] = None  # ref 0, content retained, MRU end
        self._pending_loads[b] = (h, blob, True)
        self._touch_hot(h)
        return b

    def export_sources(self, digests: Sequence[bytes]) -> List[Optional[Tuple]]:
        """Where each digest's bytes live right now, aligned with `digests`:
        ("hbm", block) for registered blocks whose content is landed,
        ("blob", bytes) for content still in flight (pending load) or only
        host-tier-resident, None when nowhere. The engine reads HBM sources
        at a step boundary, where the arrays are stable."""
        out: List[Optional[Tuple]] = []
        for h in digests:
            b = self._index.get(h)
            if b is not None:
                pending = self._pending_loads.get(b)
                if pending is not None and pending[0] == h:
                    out.append(("blob", pending[1]))
                else:
                    out.append(("hbm", b))
            elif self._tier is not None:
                blob = self._tier.peek(h)
                out.append(None if blob is None else ("blob", blob))
            else:
                out.append(None)
        return out

    def drain_loads(self) -> List[Tuple[bytes, int, object, bool]]:
        """(hash, block, bytes, remote) loads the engine must land in the
        HBM arrays before its next kernel launch — host-tier hits at
        admission (remote=False) + adopted imports (remote=True). Entries
        for since-evicted blocks were already dropped at eviction."""
        out = [
            (h, b, blob, remote)
            for b, (h, blob, remote) in self._pending_loads.items()
        ]
        self._pending_loads.clear()
        return out

    def drain_saves(self) -> List[Tuple[bytes, int]]:
        """(hash, block) eviction saves: the engine must copy these blocks'
        HBM bytes into the host tier BEFORE applying COW copies, loads, or
        kernels (the block is already reallocated — this drain order is
        what keeps the bytes readable)."""
        out, self._pending_saves = self._pending_saves, []
        return out

    def drain_cow(self) -> List[Tuple[int, int]]:
        """(src, dst) physical block copies queued by copy-on-write forks.
        The engine MUST apply these to the KV arrays before its next kernel
        launch; draining also re-exposes the sources to eviction."""
        out, self._pending_copies = self._pending_copies, []
        return out

    def free(self, seq_id: str) -> int:
        """Release a finished/preempted sequence's references. Blocks
        reaching refcount 0 return to the free list — except registered
        (full, hashed) blocks, which are RETAINED on the cached LRU list to
        serve future prefix hits until evicted. Raises KeyError on an
        unknown (or already-freed) seq_id — the double-free guard."""
        table = self._tables.pop(seq_id)  # KeyError = double free
        del self._lens[seq_id]
        self._chain.pop(seq_id, None)
        self._landed.pop(seq_id, None)
        for b in table:
            self._release_one(b)
        return len(table)

    def check_invariants(self) -> None:
        """Every block is in exactly one place (free xor cached xor live),
        refcounts match table references, and the hash index is bijective
        over registered blocks."""
        seen = set(self._free)
        assert len(seen) == len(self._free), "free list has duplicates"
        assert self.NULL_BLOCK not in seen, "null block on the free list"
        for b in self._cached:
            assert b not in seen, f"block {b} free AND cached"
            assert b in self._hash_of, f"cached block {b} has no registered hash"
            assert b not in self._ref, f"cached block {b} has live refs"
            seen.add(b)
        refs: Dict[int, int] = {}
        for sid, table in self._tables.items():
            assert len(table) == self.blocks_for(self._lens[sid]), (
                f"{sid!r}: table/len mismatch"
            )
            assert len(self._chain.get(sid, ())) <= len(table), (
                f"{sid!r}: more registered blocks than table entries"
            )
            for b in table:
                assert b not in self._free and b not in self._cached, (
                    f"block {b} live AND free/cached"
                )
                refs[b] = refs.get(b, 0) + 1
        assert refs == self._ref, (
            f"refcount drift: counted {refs}, recorded {self._ref}"
        )
        seen.update(refs)
        assert len(seen) == self.num_blocks - 1, "lost/leaked blocks"
        for h, b in self._index.items():
            assert self._hash_of.get(b) == h, f"index/hash_of drift on block {b}"
        for b, h in self._hash_of.items():
            assert self._index.get(h) == b, f"hash_of/index drift on block {b}"
        for sid, landed in self._landed.items():
            assert landed <= self._lens[sid], (
                f"{sid!r}: landed watermark {landed} past allocation "
                f"{self._lens[sid]}"
            )
        for b, (h, *_rest) in self._pending_loads.items():
            assert b not in self._free, f"pending-load block {b} on free list"
            assert self._hash_of.get(b) == h, (
                f"pending-load block {b} no longer registered under its hash"
            )
