"""Paged KV-cache block manager (reference-era analog: vLLM's BlockManager,
`vllm/core/block_manager.py` — the PagedAttention half of iteration-level
scheduling).

The physical KV cache is a fixed pool of `num_blocks` blocks of
`block_size` token slots each (the engine owns the actual [L, NB, H, BS, Dh]
arrays; this class owns only the *map*). Each live sequence holds an ordered
block table — logical token position `p` lives in physical block
`table[p // block_size]` at offset `p % block_size`. Blocks are never
shared (no prefix caching yet) and never compacted: fragmentation is
internal to the last block of each sequence only, so utilization accounting
distinguishes *allocated* slots from *used* token slots.

Admission control rides on `can_allocate`: the scheduler refuses (queues,
never crashes) a prefill whose prompt + first token doesn't fit the free
list, and preempts the youngest running sequence when decode growth hits
the budget mid-flight.

Block 0 is RESERVED as the null/scratch block: the engine pads decode
batches to bucket shapes by pointing dummy lanes' block tables at block 0,
so their writes land somewhere harmless. It is never handed out.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List


class KVCacheExhausted(RuntimeError):
    """Raised by allocate/grow when the free list cannot cover the request.

    The scheduler treats this as back-pressure (requeue/preempt), never as a
    crash — it reaches user code only on programming errors (e.g. a prompt
    longer than the whole pool, which `fits_ever` screens at submit)."""


@dataclasses.dataclass(frozen=True)
class KVStats:
    num_blocks: int          # allocatable blocks (excludes the null block)
    free_blocks: int
    used_blocks: int
    num_seqs: int
    utilization: float       # allocated fraction of the pool, 0..1


class KVBlockManager:
    """Free-list allocator mapping sequence ids to ordered block tables."""

    NULL_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self.num_blocks = num_blocks
        # Block 0 reserved; LIFO free list so recently-freed (cache-warm)
        # blocks are reused first.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[str, List[int]] = {}
        self._lens: Dict[str, int] = {}   # tokens stored per sequence

    # ------------------------------------------------------------- queries
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)  # ceil div

    def can_allocate(self, num_tokens: int) -> bool:
        return self.blocks_for(num_tokens) <= len(self._free)

    def fits_ever(self, num_tokens: int) -> bool:
        """Could this many tokens fit an EMPTY pool? (submit-time sanity)"""
        return self.blocks_for(num_tokens) <= self.num_blocks - 1

    def block_table(self, seq_id: str) -> List[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id: str) -> int:
        return self._lens[seq_id]

    def stats(self) -> KVStats:
        total = self.num_blocks - 1
        used = total - len(self._free)
        return KVStats(
            num_blocks=total,
            free_blocks=len(self._free),
            used_blocks=used,
            num_seqs=len(self._tables),
            utilization=used / total if total else 0.0,
        )

    # --------------------------------------------------------- allocation
    def allocate(self, seq_id: str, num_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of `num_tokens` tokens.

        Raises KVCacheExhausted when the free list can't cover it (the
        caller keeps the request queued) and ValueError on reuse of a live
        seq_id (a scheduler bug, not back-pressure)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has an allocation")
        if num_tokens < 1:
            raise ValueError("allocate needs >= 1 token")
        need = self.blocks_for(num_tokens)
        if need > len(self._free):
            raise KVCacheExhausted(
                f"{need} blocks needed, {len(self._free)} free"
            )
        table = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = table
        self._lens[seq_id] = num_tokens
        return list(table)

    def grow(self, seq_id: str, new_len: int) -> List[int]:
        """Extend `seq_id`'s table to cover `new_len` tokens (decode append).

        Returns the (possibly extended) block table. KVCacheExhausted when a
        new block is needed but the pool is dry — the scheduler preempts."""
        table = self._tables[seq_id]
        cur = self._lens[seq_id]
        if new_len < cur:
            raise ValueError(f"cannot shrink {seq_id!r}: {cur} -> {new_len}")
        need = self.blocks_for(new_len) - len(table)
        if need > len(self._free):
            raise KVCacheExhausted(
                f"{need} blocks needed, {len(self._free)} free"
            )
        for _ in range(need):
            table.append(self._free.pop())
        self._lens[seq_id] = new_len
        return list(table)

    def free(self, seq_id: str) -> int:
        """Return a finished/preempted sequence's blocks to the free list.

        Raises KeyError on an unknown (or already-freed) seq_id — the
        double-free guard; freed block ids are asserted absent from the
        free list before reinsertion."""
        table = self._tables.pop(seq_id)  # KeyError = double free
        del self._lens[seq_id]
        for b in table:
            assert b != self.NULL_BLOCK and b not in self._free, (
                f"block {b} double-freed (seq {seq_id!r})"
            )
            self._free.append(b)
        return len(table)

    def check_invariants(self) -> None:
        """Every block is in exactly one place: free list xor one table."""
        seen = set(self._free)
        assert len(seen) == len(self._free), "free list has duplicates"
        assert self.NULL_BLOCK not in seen, "null block on the free list"
        for sid, table in self._tables.items():
            assert len(table) == self.blocks_for(self._lens[sid]), (
                f"{sid!r}: table/len mismatch"
            )
            for b in table:
                assert b not in seen, f"block {b} owned twice"
                seen.add(b)
        assert len(seen) == self.num_blocks - 1, "lost/leaked blocks"
