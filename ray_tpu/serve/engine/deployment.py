"""`LLMDeployment` — the continuous-batching engine as a Serve replica.

Contrast with `@serve.batch` (the router-side static batch former in
`serve/handle.py`): there the ROUTER forms a fixed batch and the replica
decodes it to completion — one long request gates every short one behind
it. Here each replica runs an `InferenceEngine` driver thread and actor
methods only enqueue/drain: the ENGINE re-forms the batch every decode
iteration, so a short request submitted mid-decode joins immediately and
exits first. Use `@serve.batch` for stateless fixed-shape scoring; use
`LLMDeployment` for autoregressive generation with mixed output lengths.

The replica runs with max_concurrency > 1: a `generate` call blocked
draining its stream must not gate another caller's `submit` — the actual
compute all happens on the engine's single driver thread regardless.

`engine_options` accepts every `EngineOptions` field; the serving-throughput
knobs (see serve/README.md "Prefix caching + chunked prefill"):
`enable_prefix_caching` (default on — repeated system prompts skip straight
to their first cold KV block), `max_step_tokens` / `prefill_chunk_tokens`
(chunked prefill: long prompts land a bounded slice per iteration instead
of stalling the decode streams).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..deployment import deployment as _deployment


class _LLMReplica:
    """User-facing methods of one engine replica (wrapped by Serve's generic
    `Replica` actor; streaming rides `handle_request_streaming`)."""

    def __init__(
        self,
        model: str = "gpt2-small",
        model_overrides: Optional[Dict[str, Any]] = None,
        engine_options: Optional[Dict[str, Any]] = None,
        params=None,
    ):
        from ...models.gpt import CONFIGS
        from .engine import EngineOptions, InferenceEngine

        overrides = dict(model_overrides or {})
        if isinstance(overrides.get("dtype"), str):
            # Deployment specs travel the control plane as plain data;
            # accept "float32"/"bfloat16" and resolve to the jnp dtype here.
            import jax.numpy as jnp

            overrides["dtype"] = getattr(jnp, overrides["dtype"])
        cfg = CONFIGS[model](**overrides)
        self.engine = InferenceEngine(
            cfg,
            params=params,
            options=EngineOptions(**(engine_options or {})),
        )
        self.engine.start()

    def generate(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Blocking: returns {"tokens": [...], "finish_reason": ...}."""
        rid = self.engine.submit(prompt, max_new_tokens, eos_token=eos_token)
        out = self.engine.stream(rid)
        tokens = list(out)
        return {"tokens": tokens, "finish_reason": out.finish_reason}

    def generate_stream(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
    ):
        """Generator: one token per chunk as iterations complete — call via
        `handle.options(stream=True).generate_stream.remote(...)`."""
        rid = self.engine.submit(prompt, max_new_tokens, eos_token=eos_token)
        yield from self.engine.stream(rid)

    def __call__(self, request) -> Dict[str, Any]:
        """HTTP ingress: POST {"prompt": [ids], "max_new_tokens": n}."""
        body = request.json() if hasattr(request, "json") else dict(request)
        return self.generate(
            body["prompt"],
            int(body.get("max_new_tokens", 16)),
            body.get("eos_token"),
        )

    # ---------------------------------------------- disaggregated serving
    # Router-orchestrated handoff (serve/handle.py `_disagg_call`): the
    # router sends the prompt to a PREFILL-pool replica's prefill_handoff,
    # which computes the prompt, emits the first token, and publishes the
    # KV as a bulk-plane span descriptor; a DECODE-pool replica then runs
    # decode_imported(_stream), which adopts the descriptor's blocks into
    # its prefix cache and resubmits prompt+[first] — admission hits the
    # imported blocks, so only the tail past the last full block is
    # recomputed. Any failure at any point degrades to plain colocated
    # recompute (greedy output is identical either way — the parity gate).

    def prefill_handoff(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        eos_token: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Run the prefill here, return the first token + the exported KV
        descriptor for a decode-pool replica to import."""
        rid = self.engine.submit(prompt, 1, eos_token=eos_token)
        out = self.engine.stream(rid)
        tokens = list(out)
        finished = (
            max_new_tokens <= 1
            or not tokens
            or (eos_token is not None and tokens[-1] == eos_token)
        )
        desc = None
        if not finished:
            desc = self.engine.export_prompt_kv(prompt)
        return {
            "tokens": tokens,
            "finish_reason": "eos"
            if (eos_token is not None and tokens and tokens[-1] == eos_token)
            else out.finish_reason,
            "finished": finished,
            "descriptor": desc,
        }

    def decode_imported(
        self,
        prompt: List[int],
        first_token: int,
        max_new_tokens: int,
        eos_token: Optional[int] = None,
        descriptor: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Import the prefill replica's KV spans (best effort — failure
        means recompute) and continue the generation after `first_token`."""
        self.engine.import_blocks(descriptor)
        rid = self.engine.submit(
            list(prompt) + [int(first_token)], max_new_tokens,
            eos_token=eos_token,
        )
        out = self.engine.stream(rid)
        tokens = list(out)
        return {"tokens": tokens, "finish_reason": out.finish_reason}

    def decode_imported_stream(
        self,
        prompt: List[int],
        first_token: int,
        max_new_tokens: int,
        eos_token: Optional[int] = None,
        descriptor: Optional[Dict[str, Any]] = None,
    ):
        """Streaming variant of decode_imported (one token per chunk)."""
        self.engine.import_blocks(descriptor)
        rid = self.engine.submit(
            list(prompt) + [int(first_token)], max_new_tokens,
            eos_token=eos_token,
        )
        yield from self.engine.stream(rid)

    def engine_stats(self, include_raw: bool = False) -> Dict[str, Any]:
        return self.engine.stats(include_raw=include_raw)

    def fleet_state(self) -> Dict[str, Any]:
        """Telemetry the generic Replica piggybacks on controller health
        probes (`replica.telemetry`): queue depth, free blocks, hot-prefix
        digest, TTFT tail, recent prefix-hit rate, spec acceptance — the
        inputs to fleet routing and engine-metrics autoscaling."""
        return self.engine.fleet_state()


LLMDeployment = _deployment(
    name="LLMDeployment",
    max_ongoing_requests=64,
    ray_actor_options={"max_concurrency": 16},
)(_LLMReplica)
