"""Iteration-level scheduler (reference-era analog: Orca's iteration-level
scheduling as productized by vLLM's `core/scheduler.py`, including its
chunked-prefill step budget).

The unit of scheduling is ONE decode iteration, not one request: every call
to `schedule()` re-forms the working set — finished sequences were retired
by the engine a step earlier (their blocks already back on the free list),
queued prefills are admitted the moment the KV budget covers their prompt,
and the decode batch is whatever is RUNNING right now. A long generation
therefore never gates a short one behind it: the short request joins the
batch at the next iteration boundary and exits as soon as it hits its stop
condition.

Chunked prefill: a prompt no longer runs as one monolithic prefill. Every
step has a TOKEN budget (`max_step_tokens`); decode lanes spend one token
each and the remainder funds prefill CHUNKS (`PrefillChunk`) of at most
`prefill_chunk` tokens, so a 4k-token prompt advances a slice per step
while every decode stream keeps emitting. A sequence mid-prefill is RUNNING
but not yet decoding (`Sequence.num_computed` tracks its prefill cursor —
prefix-cache hits start it past zero); in-flight prefills continue before
new admissions so held blocks convert to tokens ASAP. Decode lanes are
funded first: chunking bounds prefill's intrusion on inter-token latency,
never the reverse.

Batch-shape discipline for XLA: decode batches are padded up to a bucket
size (powers of two up to `max_num_seqs`) and block-table widths to a
bucket width, so the jitted paged-decode program compiles once per
(batch_bucket, width_bucket) pair instead of once per working-set shape.
Prefill chunk lengths are capped at `prefill_chunk` and padded to powers of
two by the engine for the same reason. Bucketing lives here (scheduler
policy); padding lives in the engine (tensor mechanics).

Preemption: when decode growth exhausts the pool, the YOUNGEST running
sequence (last admitted — minimizes wasted work) is preempted by recompute:
its blocks are freed and it re-enters the wait queue with prompt+generated
as the new prompt, vLLM's recompute-style preemption. With prefix caching
on, its freed full blocks stay cached, so the recompute usually costs one
cache-hit re-admission rather than a real re-prefill.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .kv_manager import KVBlockManager, KVCacheExhausted

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"


@dataclasses.dataclass
class Sequence:
    """One request's generation state, host-side."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    arrival_t: float = dataclasses.field(default_factory=time.monotonic)
    output: List[int] = dataclasses.field(default_factory=list)
    state: str = WAITING
    # Prefill cursor: prompt positions with KV already landed (cache hits +
    # completed chunks). Decoding begins once it reaches len(prompt).
    num_computed: int = 0
    # Prompt tokens served straight from the prefix cache at last admission.
    num_cached: int = 0
    # Lifetime token count: unlike len(output) it survives preemption's
    # output→prompt fold, so per-token latency (TPOT) stays honest.
    num_generated: int = 0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    finish_reason: Optional[str] = None
    preemptions: int = 0

    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.output)

    @property
    def is_decoding(self) -> bool:
        """Prefill complete — this sequence rides the decode batch."""
        return self.num_computed >= len(self.prompt)

    def append_token(self, tok: int) -> None:
        if self.first_token_t is None:
            self.first_token_t = time.monotonic()
        self.output.append(tok)
        self.num_generated += 1

    def should_stop(self) -> Optional[str]:
        if len(self.output) >= self.max_new_tokens:
            return "length"
        if self.eos_token is not None and self.output and \
                self.output[-1] == self.eos_token:
            return "eos"
        return None


@dataclasses.dataclass
class PrefillChunk:
    """One step's slice of one prompt's prefill."""

    seq: Sequence
    start: int        # first prompt position this chunk computes
    num_tokens: int   # chunk length (<= scheduler.prefill_chunk)
    last: bool        # final chunk: the engine samples token 0 after it


@dataclasses.dataclass
class SchedulerOutput:
    """One iteration's work order for the engine."""

    prefills: List[PrefillChunk]   # chunk work: compute prompt[start:start+n]
    decodes: List[Sequence]        # running: one decode_step token each
    preempted: List[Sequence]      # freed + requeued this step (for logging)
    batch_bucket: int              # padded decode batch size (0 = no decode)
    width_bucket: int              # padded block-table width (blocks)
    # Speculative drafts funded this step: request_id -> draft tokens. A
    # lane with a draft runs the k+1-token verify step instead of a plain
    # decode; its draft tokens count against the step budget.
    drafts: Dict[str, List[int]] = dataclasses.field(default_factory=dict)

    @property
    def step_tokens(self) -> int:
        """Token budget actually spent this step (1/decode lane + funded
        draft tokens + prefill chunks)."""
        return (
            len(self.decodes)
            + sum(len(d) for d in self.drafts.values())
            + sum(c.num_tokens for c in self.prefills)
        )


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class Scheduler:
    def __init__(
        self,
        kv: KVBlockManager,
        max_num_seqs: int = 8,
        max_prefills_per_step: int = 1,
        max_step_tokens: int = 256,
        prefill_chunk: int = 64,
        draft_proposer=None,
        prefill_budget_cap: Optional[int] = None,
    ):
        if max_step_tokens <= max_num_seqs:
            raise ValueError(
                "max_step_tokens must exceed max_num_seqs or a full decode "
                "batch starves prefill forever"
            )
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.kv = kv
        self.max_num_seqs = max_num_seqs
        self.max_prefills_per_step = max_prefills_per_step
        self.max_step_tokens = max_step_tokens
        self.prefill_chunk = prefill_chunk
        # Role biasing (disaggregated pools, `EngineOptions.role`): a
        # DECODE-pool replica caps prefill's share of every step so the few
        # prompt tails it must recompute (import misses, degraded handoffs)
        # cannot crowd its decode lanes; None = chunking alone bounds
        # prefill intrusion (the mixed/colocated default).
        self.prefill_budget_cap = prefill_budget_cap
        # Speculative decoding (None = off): proposes draft tokens per
        # decoding lane; funded drafts ride the same step-token budget as
        # everything else (decode lanes first, drafts next, prefill last).
        self.proposer = draft_proposer
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._seqs: Dict[str, Sequence] = {}

    # ------------------------------------------------------------ intake
    def add(self, seq: Sequence) -> None:
        if seq.request_id in self._seqs:
            raise ValueError(f"duplicate request_id {seq.request_id!r}")
        # +1: the prompt's first generated token also needs a KV slot.
        if not self.kv.fits_ever(len(seq.prompt) + seq.max_new_tokens):
            raise KVCacheExhausted(
                f"request {seq.request_id!r} needs "
                f"{len(seq.prompt) + seq.max_new_tokens} KV slots but the "
                f"whole pool holds {(self.kv.num_blocks - 1) * self.kv.block_size}"
            )
        self._seqs[seq.request_id] = seq
        self.waiting.append(seq)

    def get(self, request_id: str) -> Sequence:
        return self._seqs[request_id]

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # --------------------------------------------------------- scheduling
    def finish(self, seq: Sequence, reason: str) -> None:
        """Retire a sequence NOW — its blocks hit the free list before the
        next schedule() so a queued prefill can take them this iteration."""
        seq.state = FINISHED
        seq.finish_reason = reason
        seq.finish_t = time.monotonic()
        if seq in self.running:
            self.running.remove(seq)
            self.kv.free(seq.request_id)
        del self._seqs[seq.request_id]
        if self.proposer is not None:
            self.proposer.forget(seq.request_id)

    def _chunk_for(self, seq: Sequence, budget: int) -> PrefillChunk:
        n = min(len(seq.prompt) - seq.num_computed, budget, self.prefill_chunk)
        return PrefillChunk(
            seq=seq,
            start=seq.num_computed,
            num_tokens=n,
            last=seq.num_computed + n >= len(seq.prompt),
        )

    def schedule(self) -> SchedulerOutput:
        prefills: List[PrefillChunk] = []
        preempted: List[Sequence] = []
        drafts: Dict[str, List[int]] = {}

        # Draft funding rides what's left after every decode lane gets its
        # guaranteed 1 token (conservative: preemption below only shrinks
        # the lane count).
        draft_budget = self.max_step_tokens - sum(
            1 for s in self.running if s.state == RUNNING and s.is_decoding
        )

        # 1. Grow every DECODING sequence's table for the token(s) this
        # iteration will append — one slot for the plain decode token plus
        # one per funded speculative draft; preempt the youngest on
        # exhaustion (dropping the lane's draft first: a shorter step beats
        # sacrificing someone's cache). token_ids + the computed watermark
        # let the KV manager register newly-full blocks in the prefix index
        # (KV for the latest token is not landed until the step consumes
        # it, hence num_tokens - 1). Registration progresses whenever the
        # landed watermark covers MORE full blocks than are registered —
        # speculative multi-token appends can jump past a boundary, so the
        # O(context) token-list concat is built only on that check, and the
        # register loop catches up on every missing block at once.
        for seq in list(self.running):
            if seq.state != RUNNING or not seq.is_decoding:
                continue  # mid-prefill, or preempted as a victim this loop
            landed = seq.num_tokens - 1
            reg = {}
            if landed > 0 and (
                landed // self.kv.block_size
                > self.kv.num_registered(seq.request_id)
            ):
                reg = dict(
                    token_ids=seq.prompt + seq.output, num_computed=landed
                )
            d: List[int] = []
            if self.proposer is not None and draft_budget > 0:
                # Cap: emitting accepted+1 tokens must never overshoot the
                # request's remaining generation budget. The proposer keeps
                # its own history copy — this call is O(new tokens).
                remaining = seq.max_new_tokens - len(seq.output)
                if remaining > 1:
                    d = self.proposer.propose(
                        seq.request_id, seq.prompt, seq.output,
                        min(draft_budget, remaining - 1),
                    )
            while True:
                try:
                    self.kv.grow(
                        seq.request_id, seq.num_tokens + 1 + len(d), **reg
                    )
                    break
                except KVCacheExhausted:
                    if d:
                        d = []  # drop the draft before preempting anyone
                        continue
                    victim = self._pick_victim(exclude=seq)
                    if victim is None:
                        # seq itself is the youngest — preempt it.
                        self._preempt(seq)
                        preempted.append(seq)
                        break
                    self._preempt(victim)
                    preempted.append(victim)
            if d and seq.state == RUNNING:
                drafts[seq.request_id] = d
                draft_budget -= len(d)

        decodes = [
            s for s in self.running if s.state == RUNNING and s.is_decoding
        ]
        # Decode lanes (and their funded drafts) first; prefill chunks
        # spend the remainder (capped for decode-pool replicas).
        budget = (
            self.max_step_tokens
            - len(decodes)
            - sum(len(d) for d in drafts.values())
        )
        if self.prefill_budget_cap is not None:
            budget = min(budget, self.prefill_budget_cap)

        # 2. Continue in-flight partial prefills (admission order) before
        # admitting anyone new — their blocks are already committed.
        for seq in self.running:
            if len(prefills) >= self.max_prefills_per_step or budget <= 0:
                break
            if seq.state != RUNNING or seq.is_decoding:
                continue
            chunk = self._chunk_for(seq, budget)
            prefills.append(chunk)
            budget -= chunk.num_tokens

        # 3. Admit queued prompts while lanes, KV, and budget allow.
        # FCFS: head-of-line blocking on the QUEUE is fine (arrival order is
        # fair); what iteration-level scheduling removes is blocking on the
        # multi-second decode of earlier admissions. Admission allocates the
        # WHOLE prompt (+1 for the first generated token) by prefix-cache
        # lookup first — a cached prefix starts the cursor past zero.
        while (
            self.waiting
            and len(prefills) < self.max_prefills_per_step
            and budget > 0
            and len(self.running) < self.max_num_seqs
        ):
            seq = self.waiting[0]
            try:
                _, cached = self.kv.allocate_cached(
                    seq.request_id, seq.prompt, len(seq.prompt) + 1
                )
            except KVCacheExhausted:
                break  # stays queued — refusal, not failure
            self.waiting.popleft()
            seq.state = RUNNING
            seq.num_computed = cached
            seq.num_cached = cached
            self.running.append(seq)
            chunk = self._chunk_for(seq, budget)
            prefills.append(chunk)
            budget -= chunk.num_tokens

        # A lane preempted AFTER its draft was funded must not leak a stale
        # drafts entry into the work order.
        if drafts:
            live = {s.request_id for s in decodes}
            drafts = {rid: d for rid, d in drafts.items() if rid in live}

        bb = _next_pow2(len(decodes)) if decodes else 0
        max_w = max(
            (len(self.kv.block_table(s.request_id)) for s in decodes),
            default=0,
        )
        return SchedulerOutput(
            prefills=prefills,
            decodes=decodes,
            preempted=preempted,
            batch_bucket=min(bb, _next_pow2(self.max_num_seqs)),
            width_bucket=_next_pow2(max_w) if max_w else 0,
            drafts=drafts,
        )

    def _pick_victim(self, exclude: Sequence) -> Optional[Sequence]:
        for seq in reversed(self.running):  # youngest first
            if seq is not exclude and seq.state == RUNNING:
                return seq
        return None

    def _preempt(self, seq: Sequence) -> None:
        """Recompute-style preemption: fold generated tokens into the prompt
        and requeue at the FRONT (it has seniority over never-run arrivals).
        With prefix caching, the freed full blocks stay cached — the
        "recompute" usually re-admits as cache hits."""
        self.running.remove(seq)
        self.kv.free(seq.request_id)
        # Already-generated tokens were already streamed out; fold them into
        # the prompt and shrink the remaining generation budget to match.
        seq.max_new_tokens -= len(seq.output)
        seq.prompt = seq.prompt + seq.output
        seq.output = []
        seq.state = WAITING
        seq.num_computed = 0
        seq.preemptions += 1
        self.waiting.appendleft(seq)
