"""Iteration-level scheduler (reference-era analog: Orca's iteration-level
scheduling as productized by vLLM's `core/scheduler.py`).

The unit of scheduling is ONE decode iteration, not one request: every call
to `schedule()` re-forms the working set — finished sequences were retired
by the engine a step earlier (their blocks already back on the free list),
queued prefills are admitted the moment the KV budget covers their prompt,
and the decode batch is whatever is RUNNING right now. A long generation
therefore never gates a short one behind it: the short request joins the
batch at the next iteration boundary and exits as soon as it hits its stop
condition.

Batch-shape discipline for XLA: decode batches are padded up to a bucket
size (powers of two up to `max_num_seqs`) and block-table widths to a
bucket width, so the jitted paged-decode program compiles once per
(batch_bucket, width_bucket) pair instead of once per working-set shape.
Bucketing lives here (scheduler policy); padding lives in the engine
(tensor mechanics).

Preemption: when decode growth exhausts the pool, the YOUNGEST running
sequence (last admitted — minimizes wasted work) is preempted by recompute:
its blocks are freed and it re-enters the wait queue with prompt+generated
as the new prompt, vLLM's recompute-style preemption.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .kv_manager import KVBlockManager, KVCacheExhausted

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"


@dataclasses.dataclass
class Sequence:
    """One request's generation state, host-side."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    arrival_t: float = dataclasses.field(default_factory=time.monotonic)
    output: List[int] = dataclasses.field(default_factory=list)
    state: str = WAITING
    # Lifetime token count: unlike len(output) it survives preemption's
    # output→prompt fold, so per-token latency (TPOT) stays honest.
    num_generated: int = 0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    finish_reason: Optional[str] = None
    preemptions: int = 0

    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.output)

    def append_token(self, tok: int) -> None:
        if self.first_token_t is None:
            self.first_token_t = time.monotonic()
        self.output.append(tok)
        self.num_generated += 1

    def should_stop(self) -> Optional[str]:
        if len(self.output) >= self.max_new_tokens:
            return "length"
        if self.eos_token is not None and self.output and \
                self.output[-1] == self.eos_token:
            return "eos"
        return None


@dataclasses.dataclass
class SchedulerOutput:
    """One iteration's work order for the engine."""

    prefills: List[Sequence]       # admitted this step: run prompt, emit tok 0
    decodes: List[Sequence]        # running: one decode_step token each
    preempted: List[Sequence]      # freed + requeued this step (for logging)
    batch_bucket: int              # padded decode batch size (0 = no decode)
    width_bucket: int              # padded block-table width (blocks)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class Scheduler:
    def __init__(
        self,
        kv: KVBlockManager,
        max_num_seqs: int = 8,
        max_prefills_per_step: int = 1,
    ):
        self.kv = kv
        self.max_num_seqs = max_num_seqs
        self.max_prefills_per_step = max_prefills_per_step
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._seqs: Dict[str, Sequence] = {}

    # ------------------------------------------------------------ intake
    def add(self, seq: Sequence) -> None:
        if seq.request_id in self._seqs:
            raise ValueError(f"duplicate request_id {seq.request_id!r}")
        # +1: the prompt's first generated token also needs a KV slot.
        if not self.kv.fits_ever(len(seq.prompt) + seq.max_new_tokens):
            raise KVCacheExhausted(
                f"request {seq.request_id!r} needs "
                f"{len(seq.prompt) + seq.max_new_tokens} KV slots but the "
                f"whole pool holds {(self.kv.num_blocks - 1) * self.kv.block_size}"
            )
        self._seqs[seq.request_id] = seq
        self.waiting.append(seq)

    def get(self, request_id: str) -> Sequence:
        return self._seqs[request_id]

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # --------------------------------------------------------- scheduling
    def finish(self, seq: Sequence, reason: str) -> None:
        """Retire a sequence NOW — its blocks hit the free list before the
        next schedule() so a queued prefill can take them this iteration."""
        seq.state = FINISHED
        seq.finish_reason = reason
        seq.finish_t = time.monotonic()
        if seq in self.running:
            self.running.remove(seq)
            self.kv.free(seq.request_id)
        del self._seqs[seq.request_id]

    def schedule(self) -> SchedulerOutput:
        prefills: List[Sequence] = []
        preempted: List[Sequence] = []

        # 1. Grow every running sequence's table for the token this
        # iteration will append; preempt the youngest on exhaustion.
        for seq in list(self.running):
            if seq.state != RUNNING:
                continue  # preempted as a victim earlier in this loop
            while True:
                try:
                    self.kv.grow(seq.request_id, seq.num_tokens + 1)
                    break
                except KVCacheExhausted:
                    victim = self._pick_victim(exclude=seq)
                    if victim is None:
                        # seq itself is the youngest — preempt it.
                        self._preempt(seq)
                        preempted.append(seq)
                        break
                    self._preempt(victim)
                    preempted.append(victim)

        # 2. Admit queued prefills while the batch and KV budget allow.
        # FCFS: head-of-line blocking on the QUEUE is fine (arrival order is
        # fair); what iteration-level scheduling removes is blocking on the
        # multi-second decode of earlier admissions.
        while (
            self.waiting
            and len(prefills) < self.max_prefills_per_step
            # running already includes this step's admissions (appended
            # below) — adding len(prefills) would double-count them.
            and len(self.running) < self.max_num_seqs
        ):
            seq = self.waiting[0]
            try:
                # Prompt + the first generated token, so admission never
                # immediately re-triggers a preemption cycle.
                self.kv.allocate(seq.request_id, len(seq.prompt) + 1)
            except KVCacheExhausted:
                break  # stays queued — refusal, not failure
            self.waiting.popleft()
            seq.state = RUNNING
            prefills.append(seq)
            self.running.append(seq)

        decodes = [s for s in self.running if s not in prefills]
        bb = _next_pow2(len(decodes)) if decodes else 0
        max_w = max(
            (len(self.kv.block_table(s.request_id)) for s in decodes),
            default=0,
        )
        return SchedulerOutput(
            prefills=prefills,
            decodes=decodes,
            preempted=preempted,
            batch_bucket=min(bb, _next_pow2(self.max_num_seqs)),
            width_bucket=_next_pow2(max_w) if max_w else 0,
        )

    def _pick_victim(self, exclude: Sequence) -> Optional[Sequence]:
        for seq in reversed(self.running):  # youngest first
            if seq is not exclude:
                return seq
        return None

    def _preempt(self, seq: Sequence) -> None:
        """Recompute-style preemption: fold generated tokens into the prompt
        and requeue at the FRONT (it has seniority over never-run arrivals)."""
        self.running.remove(seq)
        self.kv.free(seq.request_id)
        # Already-generated tokens were already streamed out; fold them into
        # the prompt and shrink the remaining generation budget to match.
        seq.max_new_tokens -= len(seq.output)
        seq.prompt = seq.prompt + seq.output
        seq.output = []
        seq.state = WAITING
        seq.preemptions += 1
        self.waiting.appendleft(seq)
