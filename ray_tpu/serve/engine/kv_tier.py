"""Host-RAM KV tier — the storage layer below HBM in the tiered prefix
cache (reference-era analogs: Mooncake's DRAM tier of its disaggregated
KVCache pool, vLLM's CPU swap space — here content-addressed by the SAME
chained blake2b digest the HBM index uses, so the two tiers and the fleet
transfer plane share one global address).

An HBM eviction no longer kills a prefix: the engine copies the block's
bytes here (`KVBlockManager.drain_saves`) and the digest stays advertised
in the replica's hot-prefix digest — the fleet router keeps steering
matching prompts at this replica, where `allocate_cached`'s tier consult
turns the re-admission into a host->HBM memcpy instead of a recompute.
Export (`engine.export_prompt_kv`) also serves from here, so content that
fell out of HBM remains pullable by every other replica over the bulk
plane: the tier is what makes the cluster-wide cache TIERED rather than
merely distributed.

Eviction is LRU under a byte budget (`EngineOptions.host_kv_bytes`,
per-replica). `on_evict` notifies the block manager so the digest stops
being advertised once the bytes are truly gone. All access runs under the
engine lock — the tier itself is a plain OrderedDict, no locking here.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional


class HostKVTier:
    """Content-addressed LRU byte store: digest -> one block's KV bytes
    (a contiguous ndarray the engine packs/unpacks; the tier never looks
    inside)."""

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError("host tier needs a positive byte budget")
        self.budget_bytes = int(budget_bytes)
        self._blobs: "OrderedDict[bytes, object]" = OrderedDict()
        self.bytes_used = 0
        # Set by the block manager: called with each digest the budget
        # sweep evicts (stops its hot-hash advertisement).
        self.on_evict: Optional[Callable[[bytes], None]] = None
        self.hits = 0
        self.saves = 0
        self.evictions = 0

    @property
    def blocks(self) -> int:
        return len(self._blobs)

    def contains(self, h: bytes) -> bool:
        return h in self._blobs

    def peek(self, h: bytes):
        """Read without touching recency (export path: serving a remote
        pull must not make content look locally hot)."""
        return self._blobs.get(h)

    def get(self, h: bytes):
        """Read + touch MRU (admission path: a consult that feeds a real
        sequence is a use)."""
        blob = self._blobs.get(h)
        if blob is not None:
            self._blobs.move_to_end(h)
            self.hits += 1
        return blob

    def put(self, h: bytes, blob) -> bool:
        """Store one block's bytes; LRU-evicts to the byte budget. A blob
        larger than the whole budget is refused (never thrash the entire
        tier for one block)."""
        n = int(getattr(blob, "nbytes", len(blob)))
        if n > self.budget_bytes:
            return False
        if h in self._blobs:
            self._blobs.move_to_end(h)
            return True
        self._blobs[h] = blob
        self.bytes_used += n
        self.saves += 1
        while self.bytes_used > self.budget_bytes and len(self._blobs) > 1:
            old_h, old = self._blobs.popitem(last=False)
            self.bytes_used -= int(getattr(old, "nbytes", len(old)))
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(old_h)
        return True
