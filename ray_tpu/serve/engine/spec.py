"""Prompt-lookup (n-gram) draft proposer for speculative decoding.

No second model: the draft source is the sequence's OWN token history
(prompt + generated). If the last `n` tokens also occur earlier in the
history, the tokens that followed that earlier occurrence are proposed as
the draft — long verbatim spans (quoting the prompt, boilerplate, greedy
repetition loops) verify at near-100% acceptance, and the paged verify
step (`models/gpt.py:verify_step_paged`) scores all k drafts in ONE
forward instead of k sequential decode dispatches.

The proposer is incremental: each sequence carries a (ngram -> latest
start position) index that advances as tokens append, so a propose() call
costs O(new tokens), not O(context). Preemption folds generated tokens
into the prompt WITHOUT changing the token list, so the index survives
preemption untouched.

Pure host-side policy — no JAX; the scheduler funds accepted drafts inside
its step-token budget and the engine verifies them.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


class NGramProposer:
    """One deployment-wide proposer; per-sequence state keyed by request id
    (dropped via `forget` when the sequence retires)."""

    def __init__(self, k: int = 4, n: int = 2):
        if k < 1:
            raise ValueError("spec draft length k must be >= 1")
        if n < 1:
            raise ValueError("ngram match length n must be >= 1")
        self.k = k
        self.n = n
        # request_id -> [ngram -> latest start position, private history
        # copy, positions indexed]. The proposer keeps its OWN history so
        # the per-step scheduler call hands over only (prompt, output)
        # references — no O(context) concat per decode lane per step.
        self._state: Dict[str, list] = {}

    def propose(
        self,
        request_id: str,
        prompt: Sequence[int],
        output: Sequence[int],
        max_draft: int,
    ) -> List[int]:
        """Draft up to `min(k, max_draft)` tokens likely to follow the
        sequence. Returns [] when the trailing n-gram has no earlier
        occurrence (or the context is too short) — the engine then runs a
        plain decode step for this lane. Costs O(tokens appended since the
        last call): new tokens only ever appear at the tail of `output`
        (preemption folds output into prompt WITHOUT changing the token
        list, so the retained history stays valid)."""
        limit = min(self.k, max_draft)
        n = self.n
        total = len(prompt) + len(output)
        if limit < 1 or total < n + 1:
            return []
        st = self._state.get(request_id)
        if st is None:
            hist = [int(t) for t in prompt]
            hist += [int(t) for t in output]
            st = self._state[request_id] = [{}, hist, 0]
        else:
            hist = st[1]
            delta = total - len(hist)
            if delta > 0:
                hist.extend(int(t) for t in output[len(output) - delta:])
        index, hist, consumed = st
        # Index every n-gram that starts strictly BEFORE the trailing one —
        # matching the suffix against itself would propose the suffix.
        for i in range(consumed, total - n):
            index[tuple(hist[i:i + n])] = i
        st[2] = max(consumed, total - n)
        p = index.get(tuple(hist[total - n:]))
        if p is None:
            return []
        return list(hist[p + n:p + n + limit])

    def forget(self, request_id: str) -> None:
        self._state.pop(request_id, None)

    def __len__(self) -> int:
        return len(self._state)
