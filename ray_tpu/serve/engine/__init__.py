"""Continuous-batching LLM inference engine (vLLM-style iteration-level
scheduling + paged KV cache) — see `ray_tpu/serve/README.md`.

Layering:
  * `kv_manager` — paged KV block map: free list, per-sequence block
    tables, admission-by-budget (no JAX imports).
  * `scheduler` — iteration-level working-set former: admit / retire /
    preempt every decode step; shape buckets for XLA (no JAX imports).
  * `engine` — the driver loop over `models/gpt.py`'s
    `prefill_paged` / `decode_step_paged`, streaming tokens per iteration.
  * `deployment` — `LLMDeployment`, the engine wired through the Serve
    controller/router/streaming planes.

`InferenceEngine` / `LLMDeployment` import JAX and the model stack, so they
resolve lazily; the schedulers stay importable in lightweight contexts.
"""

from .kv_manager import KVBlockManager, KVCacheExhausted, KVStats
from .scheduler import PrefillChunk, Scheduler, SchedulerOutput, Sequence

__all__ = [
    "KVBlockManager",
    "KVCacheExhausted",
    "KVStats",
    "PrefillChunk",
    "Scheduler",
    "SchedulerOutput",
    "Sequence",
    "EngineOptions",
    "InferenceEngine",
    "RequestOutput",
    "LLMDeployment",
]

_LAZY = {
    "EngineOptions": "engine",
    "InferenceEngine": "engine",
    "RequestOutput": "engine",
    "LLMDeployment": "deployment",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
