"""Continuous-batching LLM inference engine (vLLM-style iteration-level
scheduling + paged KV cache) — see `ray_tpu/serve/README.md`.

Layering:
  * `kv_manager` — paged KV block map: free list, per-sequence block
    tables, admission-by-budget, hot-prefix digest (no JAX imports).
  * `scheduler` — iteration-level working-set former: admit / retire /
    preempt every decode step; shape buckets for XLA; speculative draft
    funding inside the step budget (no JAX imports).
  * `spec` — n-gram prompt-lookup draft proposer for speculative decoding
    (no JAX imports).
  * `engine` — the driver loop over `models/gpt.py`'s `prefill_paged` /
    `decode_step_paged` / `verify_step_paged`, streaming tokens per
    iteration.
  * `deployment` — `LLMDeployment`, the engine wired through the Serve
    controller/router/streaming planes (`fleet_state` telemetry feeds the
    fleet routing/autoscaling planes in `serve/fleet/`).

`InferenceEngine` / `LLMDeployment` import JAX and the model stack, so they
resolve lazily; the schedulers stay importable in lightweight contexts.
"""

from .kv_manager import KVBlockManager, KVCacheExhausted, KVStats
from .scheduler import PrefillChunk, Scheduler, SchedulerOutput, Sequence

__all__ = [
    "KVBlockManager",
    "KVCacheExhausted",
    "KVStats",
    "PrefillChunk",
    "Scheduler",
    "SchedulerOutput",
    "Sequence",
    "EngineOptions",
    "InferenceEngine",
    "RequestOutput",
    "LLMDeployment",
]

_LAZY = {
    "EngineOptions": "engine",
    "InferenceEngine": "engine",
    "RequestOutput": "engine",
    "LLMDeployment": "deployment",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
