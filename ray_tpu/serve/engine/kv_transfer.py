"""KV block transfer — computed prefix KV shipped between replicas as
`(name, offset, length)` spans over the bulk plane.

Same wire idiom as the data plane's shuffle transport (`data/transport.py`,
PR 8): the exporter packs its blocks into ONE pickle-5 frame whose
out-of-band buffers are the per-block byte arrays laid out contiguously
(`serialization.pack` wire format:
``[u32 npayload][payload][u32 nbufs]{[u64 len][buffer]}*``), stores the
frame as a first-class arena object (`ClusterBackend.put_serialized`), and
publishes a small DESCRIPTOR: the span table keyed by the kv_manager's
chained blake2b digests — the SAME global content address the prefix
index, the fleet router, and the host tier all use — plus the pinning
ObjectRef and the producer-local store name.

Import fallback ladder (each rung correctness-preserving; the last rung is
exactly today's behavior):

  * descriptor carries ``inline`` bytes (no cluster backend / local mode)
    -> use them directly;
  * SAME-node consumer -> ``local_store.read(name)``: the blobs come back
    as zero-copy numpy views over the producer's arena mapping;
  * cross-node -> ``object_sources`` resolves a live copy, then the needed
    blocks' spans coalesce into contiguous runs pulled with
    ``bulk.pull_span`` (native off-GIL lander when built) into a scratch
    store object;
  * anything fails -> None: the caller imports nothing and the sequence
    RECOMPUTES its prefill — degraded mode is the pre-disaggregation path.

All-or-nothing: a fetch that cannot produce EVERY requested block returns
None rather than a partial set, so a crashed exporter can never leave a
half-imported prefix behind (the chaos gate in tests/test_serve_disagg.py).
"""

from __future__ import annotations

import os
import pickle
import secrets
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

DESCRIPTOR_VERSION = 1


def _rebuild_blob(dtype_str: str, shape, buf) -> np.ndarray:
    """Zero-copy view over whatever buffer the unpickler hands us (the
    arena mapping on a same-node read)."""
    return np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)


class _OOBBlock:
    """Wraps one block's contiguous byte array so it travels as ONE
    out-of-band pickle-5 buffer at a knowable frame offset."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray):
        self.arr = arr

    def __reduce__(self):
        return (
            _rebuild_blob,
            (self.arr.dtype.str, self.arr.shape, pickle.PickleBuffer(self.arr)),
        )


def _backend():
    # _runtime_or_attach, never _global_runtime: an engine used outside a
    # cluster (unit tests, plain scripts) must degrade to inline
    # descriptors, not BOOT a local runtime as a side effect (the PR 7
    # metrics-leak class).
    try:
        from ...core import api

        rt = api._runtime_or_attach()
        return rt.backend if rt is not None else None
    except Exception:  # noqa: BLE001 — no runtime (engine unit tests)
        return None


# ------------------------------------------------------------------ export
def export_descriptor(
    digests: Sequence[bytes],
    blobs: Sequence[np.ndarray],
    sig: str,
    block_size: int,
) -> Optional[Dict[str, Any]]:
    """Store `blobs` (one contiguous array per digest, chain order) as one
    arena segment and return the span descriptor. Degrades to an inline
    descriptor (bytes embedded) without a span-capable backend."""
    if not digests:
        return None
    blobs = [np.ascontiguousarray(b) for b in blobs]
    base = {
        "v": DESCRIPTOR_VERSION,
        "sig": sig,
        "block_size": int(block_size),
        "dtype": blobs[0].dtype.str,
        "shape": tuple(blobs[0].shape),
        "digests": [h.hex() for h in digests],
    }
    backend = _backend()
    put_serialized = getattr(backend, "put_serialized", None)
    if put_serialized is None:
        return {**base, "inline": [b.tobytes() for b in blobs]}

    from ...core import api

    payload, buffers, spans = pack_frame(base["digests"], blobs)
    rt = api._runtime_or_attach()
    ref, name, span_ok = put_serialized(
        payload, buffers, rt.current_task_id.hex()
    )
    if not span_ok:
        spans = None  # inline/head frame: span-addressed reads impossible
    return {**base, "ref": ref, "name": name, "spans": spans}


def pack_frame(digests_hex: Sequence[str], blobs: Sequence[np.ndarray]):
    """(payload, out-of-band buffers, spans) of one export frame — the
    k-th buffer is the k-th block, so span k addresses digest k's bytes
    inside the stored object. Shared by export_descriptor and the
    kv-transfer perf gate (which drives a store+BulkServer directly)."""
    from ...core import serialization

    wrapped = {"digests": list(digests_hex),
               "blocks": [_OOBBlock(b) for b in blobs]}
    payload, buffers = serialization.serialize(wrapped)
    spans: Optional[List[Tuple[int, int]]] = None
    if len(buffers) == len(blobs):
        # Frame layout: [u32 npayload][payload][u32 nbufs] then per buffer
        # [u64 len][bytes]; the k-th buffer is the k-th block, in order.
        cur = 4 + len(payload) + 4
        spans = []
        for b in buffers:
            n = b.raw().nbytes
            spans.append((cur + 8, n))
            cur += 8 + n
    return payload, buffers, spans


# ------------------------------------------------------------------ import
def _runs(idx: List[int], spans: List[Tuple[int, int]]) -> List[Tuple[int, int, List[int]]]:
    """Coalesce needed block indices into contiguous byte runs:
    (run_offset, run_length, member indices). Blocks are laid out in digest
    order with an 8-byte length header between them, so adjacent needed
    blocks merge into one bulk pull."""
    out: List[Tuple[int, int, List[int]]] = []
    for k in idx:
        off, n = spans[k]
        if out and off <= out[-1][0] + out[-1][1] + 8:
            po, pn, members = out.pop()
            out.append((po, off + n - po, members + [k]))
        else:
            out.append((off, n, [k]))
    return out


def _fetch_remote_runs(
    src: dict, desc: Dict[str, Any], needed: List[int], timeout_s: float,
    store=None,
) -> Optional[Dict[int, np.ndarray]]:
    """Pull the needed blocks' spans from the source's bulk server into a
    scratch store object (native lander path), slice out each block, and
    COPY it to private memory (the scratch is released before return)."""
    from ...core import bulk as bulk_mod

    if store is None:
        store = getattr(_backend(), "local_store", None)
    spans = desc["spans"]
    dtype = np.dtype(desc["dtype"])
    shape = tuple(desc["shape"])
    out: Dict[int, np.ndarray] = {}
    for run_off, run_len, members in _runs(needed, spans):
        if store is not None:
            sname, writer = store.create_begin(secrets.token_hex(28), run_len)
            try:
                bulk_mod.pull_span(
                    src["bulk"], src["name"], run_off, run_len, writer,
                    timeout_s,
                )
                writer.commit()
                raw = store.read_raw(sname)
                view = memoryview(raw)
                for k in members:
                    off, n = spans[k]
                    rel = off - run_off
                    out[k] = np.frombuffer(
                        view[rel:rel + n], dtype=dtype
                    ).reshape(shape).copy()
            finally:
                try:
                    store.release(sname, unlink=True)
                except Exception:  # noqa: BLE001
                    pass
        else:
            for k in members:
                off, n = spans[k]
                buf = bulk_mod.fetch_span_bytes(
                    src["bulk"], src["name"], off, n, timeout_s
                )
                out[k] = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return out


def fetch_blocks(
    desc: Dict[str, Any],
    needed_hex: Sequence[str],
    timeout_s: float = 10.0,
) -> Optional[List[Tuple[str, np.ndarray]]]:
    """Materialize the requested digests' block bytes, all or nothing.
    Returns [(digest_hex, blob)] in `needed_hex` order, or None on any
    failure (the caller recomputes — degraded mode is today's behavior)."""
    if not needed_hex:
        return []
    digests: List[str] = desc.get("digests") or []
    pos = {h: i for i, h in enumerate(digests)}
    try:
        idx = [pos[h] for h in needed_hex]
    except KeyError:
        return None  # descriptor doesn't carry a requested digest

    from ...util import flight

    # The exporter stamped its trace id on the descriptor, so this span
    # (and the bulk.pull spans nested under the span_pull rung) lands in
    # the same x-request-id forest as the prefill that produced the KV.
    trace = desc.get("trace")
    t0 = flight.now_ns()

    def _done(result, rung: str):
        flight.record(
            "kv.fetch", t0, flight.now_ns(), trace=trace,
            lane="serve/kv", flow=f"disagg/{trace}" if trace else None,
            attrs={"rung": rung, "blocks": len(idx),
                   "ok": result is not None})
        return result

    inline = desc.get("inline")
    if inline is not None:
        dtype = np.dtype(desc["dtype"])
        shape = tuple(desc["shape"])
        try:
            return _done([
                (needed_hex[j],
                 np.frombuffer(inline[i], dtype=dtype).reshape(shape))
                for j, i in enumerate(idx)
            ], "inline")
        except Exception:  # noqa: BLE001
            return _done(None, "inline")

    backend = _backend()
    if backend is None:
        return None
    # Test/diagnostic knob: force the bulk span-pull rung even same-node
    # (proves the cross-machine path on a one-box cluster).
    force_span = os.environ.get("RAY_TPU_KV_FORCE_SPAN_PULL") == "1"

    # Rung 1: same-node zero-copy read straight off the producer's arena.
    name = desc.get("name")
    store = getattr(backend, "local_store", None)
    if name and store is not None and not force_span:
        try:
            wrapped = store.read(name)
            blocks = wrapped["blocks"]
            return _done(
                [(needed_hex[j], blocks[i]) for j, i in enumerate(idx)],
                "local")
        except Exception:  # noqa: BLE001 — not local / gone; pull spans
            pass

    # Rung 2: resolve a live copy and pull only the needed spans.
    spans = desc.get("spans")
    ref = desc.get("ref")
    sources_of = getattr(backend, "object_sources", None)
    if spans is not None and ref is not None and sources_of is not None:
        try:
            src = sources_of([ref.id.hex()])[0]
        except Exception:  # noqa: BLE001
            src = None
        if src:
            try:
                got = _fetch_remote_runs(src, desc, idx, timeout_s)
            except Exception:  # noqa: BLE001 — source died/evicted mid-read
                got = None
            if got is not None and len(got) == len(idx):
                return _done(
                    [(needed_hex[j], got[i]) for j, i in enumerate(idx)],
                    "span_pull")

    # Rung 3: whole-object get (borrow/map zero-copy same host, classic
    # transfer otherwise; lineage re-execution absorbs eviction).
    if ref is not None and not force_span:
        try:
            from ...core import api

            wrapped = api.get(ref, timeout=timeout_s)
            blocks = wrapped["blocks"]
            return _done(
                [(needed_hex[j], blocks[i]) for j, i in enumerate(idx)],
                "object_get")
        except Exception:  # noqa: BLE001
            return _done(None, "object_get")
    return _done(None, "none")
