"""Continuous-batching inference engine (reference-era analog: vLLM's
`LLMEngine.step()` loop — Orca-style iteration-level scheduling over a
PagedAttention cache, here driving `models/gpt.py`'s paged decode path).

One `step()` is one model iteration:

    1. `Scheduler.schedule()` re-forms the working set — admits queued
       prompts the moment the KV budget (free + reclaimable cached blocks)
       covers them, preempts on exhaustion (finished sequences were already
       retired and their blocks freed at the END of the previous step).
       Admission allocates by PREFIX-CACHE lookup first: a prompt whose
       leading full blocks are already resident skips straight to the first
       cold token.
    2. Prefill advances in CHUNKS under a per-step token budget (one jitted
       program per (chunk, width) bucket): each step lands at most
       `prefill_chunk_tokens` of one prompt, so a long prompt never stalls
       the decode streams for a monolithic prefill. The final chunk emits
       the first token — that's TTFT, decoupled from everything else in
       flight.
    3. All fully-prefilled sequences advance one token through ONE jitted
       `decode_step_paged` call — batch padded to a power-of-two lane
       bucket and block-table width bucket, so XLA compiles a bounded set
       of programs no matter how the working set churns.
    4. New tokens stream to per-request output queues; sequences hitting
       their stop condition retire immediately, returning their blocks for
       the NEXT step's admissions.

The engine owns a dedicated driver thread (all JAX compute on one thread);
`submit()`/`stream()` are called from any thread — replica actor method
threads under Serve (`LLMDeployment` runs with max_concurrency > 1 so a
blocked `generate` never gates another request's `submit`).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from collections import deque

from ...util.metrics import quantile as _quantile
from .kv_manager import KVBlockManager
from .scheduler import Scheduler, Sequence, SchedulerOutput, _next_pow2

_FINISH = object()  # stream sentinel

# Jitted paged kernels are process-wide singletons: every engine (and every
# replica in local-mode tests) shares one XLA program cache, keyed by the
# (cfg, shape-bucket) signature jax.jit already tracks. Re-wrapping per
# engine would recompile identical programs per instance.
_JITS = None


def _paged_jits():
    global _JITS
    if _JITS is None:
        import jax

        from ...models.gpt import (
            decode_step_paged,
            prefill_paged,
            verify_step_paged,
        )

        _JITS = (
            jax.jit(prefill_paged, static_argnums=(6,), donate_argnums=(5,)),
            jax.jit(decode_step_paged, static_argnums=(5,), donate_argnums=(4,)),
            jax.jit(verify_step_paged, static_argnums=(6,), donate_argnums=(5,)),
        )
    return _JITS


@dataclasses.dataclass(frozen=True)
class EngineOptions:
    num_blocks: int = 64          # physical KV blocks (incl. null block 0)
    block_size: int = 16          # token slots per block
    max_num_seqs: int = 8         # decode-batch lane ceiling
    max_prefills_per_step: int = 1
    # Chunked prefill: per-step token budget (decode lanes cost 1 each,
    # prefill chunks spend the rest) and the per-chunk length cap — a long
    # prompt lands `prefill_chunk_tokens` per step instead of stalling every
    # decode stream for one monolithic prefill.
    max_step_tokens: int = 256
    prefill_chunk_tokens: int = 64
    # Automatic prefix caching: full KV blocks are content-hashed and
    # shared; a prompt whose prefix is cached skips straight to the first
    # cold block. Freed blocks are retained (reclaimable, LRU-evicted).
    enable_prefix_caching: bool = True
    # Speculative decoding (greedy only): per-lane draft length k proposed
    # by n-gram prompt lookup (spec.py) and scored in ONE verify forward
    # (`verify_step_paged`) — up to k+1 tokens emitted per step per lane.
    # 0 disables. Draft tokens are funded inside `max_step_tokens`.
    spec_tokens: int = 0
    spec_ngram: int = 2
    temperature: float = 0.0      # 0 = greedy
    seed: int = 0
    # Disaggregated serving (serve/README.md "Disaggregated serving"):
    # "mixed" (default — exactly the pre-disagg engine), "prefill" (step
    # budget biased toward prefill chunks: this replica computes prompts,
    # emits the first token, and hands decode off), or "decode" (prefill's
    # per-step share capped at max_step_tokens/4 so recompute tails can't
    # crowd the decode lanes).
    role: str = "mixed"
    # Host-RAM KV tier budget (bytes, per replica; 0 disables): HBM-evicted
    # registered blocks are SAVED here instead of dying, stay advertised in
    # the hot-prefix digest, serve allocate_cached on an HBM miss, and are
    # exportable to other replicas over the bulk plane.
    host_kv_bytes: int = 32 << 20
    # Deadline for one KV export/import (span fetch + handoff plumbing).
    kv_transfer_timeout_s: float = 30.0


class RequestOutput:
    """Per-request stream endpoint: the engine thread feeds it, any
    consumer thread drains it."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._q: "queue.Queue" = queue.Queue()
        self.finish_reason: Optional[str] = None
        # Registry-cleanup handshake (under the engine lock): the engine
        # drops the registry entry once the request is BOTH finished and
        # retrieved, whichever happens first — a fast request may finish
        # before its caller ever reaches stream().
        self.finished = False
        self.retrieved = False

    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _FINISH:
                return
            if isinstance(item, Exception):
                raise item
            yield item


class InferenceEngine:
    def __init__(
        self,
        cfg,
        params=None,
        options: Optional[EngineOptions] = None,
    ):
        import jax

        from ...models.gpt import init_paged_cache, init_params

        self.cfg = dataclasses.replace(cfg, remat=False, remat_policy=None)
        self.opts = options or EngineOptions()
        if self.opts.role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"role must be mixed|prefill|decode, got {self.opts.role!r}"
            )
        self._jnp = jax.numpy
        if params is None:
            params = init_params(jax.random.PRNGKey(self.opts.seed), cfg)
        self.params = params
        self.kv = init_paged_cache(
            self.cfg, self.opts.num_blocks, self.opts.block_size
        )
        self.host_tier = None
        if self.opts.host_kv_bytes > 0 and self.opts.enable_prefix_caching:
            from .kv_tier import HostKVTier

            self.host_tier = HostKVTier(self.opts.host_kv_bytes)
        self.block_manager = KVBlockManager(
            self.opts.num_blocks,
            self.opts.block_size,
            enable_prefix_caching=self.opts.enable_prefix_caching,
            host_tier=self.host_tier,
        )
        proposer = None
        if self.opts.spec_tokens > 0:
            if self.opts.temperature > 0.0:
                # The greedy accept rule (longest matching draft prefix +
                # one corrective token) only reproduces GREEDY decode;
                # sampled decode would need rejection sampling.
                raise ValueError(
                    "speculative decoding requires temperature=0 (greedy)"
                )
            from .spec import NGramProposer

            proposer = NGramProposer(
                k=self.opts.spec_tokens, n=self.opts.spec_ngram
            )
        # Role biasing: a prefill-pool replica runs several chunks per step
        # (its decode lanes are single-token handoff stubs); a decode-pool
        # replica caps prefill's per-step share so recompute tails (import
        # misses, degraded handoffs) can't crowd the decode lanes.
        mpps = self.opts.max_prefills_per_step
        prefill_cap = None
        if self.opts.role == "prefill":
            mpps = max(mpps, 4)
        elif self.opts.role == "decode":
            prefill_cap = max(
                self.opts.prefill_chunk_tokens, self.opts.max_step_tokens // 4
            )
        self.scheduler = Scheduler(
            self.block_manager,
            max_num_seqs=self.opts.max_num_seqs,
            max_prefills_per_step=mpps,
            max_step_tokens=self.opts.max_step_tokens,
            prefill_chunk=self.opts.prefill_chunk_tokens,
            draft_proposer=proposer,
            prefill_budget_cap=prefill_cap,
        )
        # cfg is static (hashable frozen dataclass); kv buffers are donated
        # — each call consumes self.kv and hands back its successor.
        self._prefill, self._decode, self._verify = _paged_jits()
        import numpy as np

        self._np = np
        self._sample_rng = np.random.default_rng(self.opts.seed)
        self._lock = threading.Lock()          # scheduler + queues
        self._work = threading.Condition(self._lock)
        self._outputs: Dict[str, RequestOutput] = {}
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Rolling throughput/latency accounting (host-side, cheap). The
        # latency windows are bounded — a long-lived replica must not
        # accumulate one float per request forever.
        self.total_tokens = 0
        self.total_preemptions = 0
        self.total_finished = 0
        self.total_spec_proposed = 0
        self.total_spec_accepted = 0
        self.total_blocks_imported = 0
        self.total_blocks_exported = 0
        # Side work serviced by the driver thread at step boundaries, where
        # self.kv is stable (kernel donation invalidates old buffers, so no
        # other thread may ever read the KV arrays): ("export", digests,
        # Future) entries from export_prompt_kv.
        self._side_work: "deque" = deque()
        self._ttfts: "deque[float]" = deque(maxlen=1024)
        self._tpots: "deque[float]" = deque(maxlen=1024)
        self._step_ttfts: List[float] = []     # reset each step()
        self._step_tpots: List[float] = []
        self._step_spec = [0, 0]               # [proposed, accepted]
        self._tok_window: List[float] = []     # token-emit timestamps
        # (t, hits, misses) snapshots — fleet_state's RECENT hit-rate
        # window, the autoscaler's cache-cold signal.
        self._hit_snaps: "deque" = deque(maxlen=64)
        # request_id -> {trace, submit_t, admit_t, first_t} (wall-clock):
        # per-request span bookkeeping for traced (Serve) submissions —
        # untraced submits (engine unit tests, direct callers) skip it.
        self._trace_info: Dict[str, Dict[str, Any]] = {}
        self._init_metrics()

    # ------------------------------------------------------------- metrics
    def _init_metrics(self):
        try:
            from ...util.metrics import Counter, Gauge, Histogram

            self._m_queue = Gauge(
                "serve_engine_queue_depth", "prompts waiting for KV admission"
            )
            self._m_running = Gauge(
                "serve_engine_running_seqs", "sequences in the decode batch"
            )
            self._m_kv = Gauge(
                "serve_engine_kv_utilization", "allocated fraction of KV blocks"
            )
            self._m_tps = Gauge(
                "serve_engine_tokens_per_s", "generated tokens/s (10s window)"
            )
            self._m_tokens = Counter(
                "serve_engine_tokens_total", "tokens generated"
            )
            self._m_preempt = Counter(
                "serve_engine_preemptions_total", "recompute preemptions"
            )
            self._m_ttft = Histogram(
                "serve_engine_ttft_s", "time to first token"
            )
            self._m_tpot = Histogram(
                "serve_engine_tpot_s", "time per output token after the first"
            )
            self._m_pc_hits = Counter(
                "serve_engine_prefix_cache_hits_total",
                "KV blocks served from the prefix cache",
            )
            self._m_pc_misses = Counter(
                "serve_engine_prefix_cache_misses_total",
                "cacheable KV blocks that had to be computed",
            )
            self._m_pc_evict = Counter(
                "serve_engine_prefix_cache_evictions_total",
                "cached KV blocks reclaimed for new allocations",
            )
            self._m_step_tokens = Histogram(
                "serve_engine_step_budget_tokens",
                "tokens scheduled per engine step "
                "(decode lanes + prefill chunk tokens)",
                boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            )
            self._m_spec_prop = Counter(
                "serve_engine_spec_proposed_total",
                "speculative draft tokens scored by the verify step",
            )
            self._m_spec_acc = Counter(
                "serve_engine_spec_accepted_total",
                "speculative draft tokens accepted (emitted without a "
                "dedicated decode step)",
            )
            self._m_host_hits = Counter(
                "serve_engine_host_tier_hits_total",
                "prefix-cache hits served from the host-RAM KV tier",
            )
            self._m_host_bytes = Gauge(
                "serve_engine_host_tier_bytes",
                "bytes resident in the host-RAM KV tier",
            )
            self._m_kv_import = Counter(
                "serve_engine_kv_blocks_imported_total",
                "KV blocks imported from other replicas (disagg handoff / "
                "cluster-wide prefix cache)",
            )
            self._m_kv_export = Counter(
                "serve_engine_kv_blocks_exported_total",
                "KV blocks exported as bulk-plane span segments",
            )
            # Counters export monotonic increments; the KV manager keeps
            # lifetime totals — ship deltas since the last step.
            self._kv_exported = {"hits": 0, "misses": 0, "evictions": 0,
                                 "host_hits": 0, "imported": 0, "exported": 0}
            try:
                # Under Serve, tag every series with its replica so scrapes
                # distinguish replicas and the controller can prune a
                # drained replica's series (serve/controller._drain).
                from ..context import get_replica_context

                ctx = get_replica_context()
                tags = {"app": ctx.app_name, "deployment": ctx.deployment,
                        "replica": ctx.replica_tag,
                        "role": self.opts.role}
                for m in (self._m_queue, self._m_running, self._m_kv,
                          self._m_tps, self._m_tokens, self._m_preempt,
                          self._m_ttft, self._m_tpot, self._m_pc_hits,
                          self._m_pc_misses, self._m_pc_evict,
                          self._m_step_tokens, self._m_spec_prop,
                          self._m_spec_acc, self._m_host_hits,
                          self._m_host_bytes, self._m_kv_import,
                          self._m_kv_export):
                    m.set_default_tags(tags)
            except Exception:  # noqa: BLE001 — engine used outside Serve
                pass
        except Exception:  # noqa: BLE001 — metrics are never load-bearing
            self._m_queue = None

    def _export_metrics(self, stats: Dict[str, Any]):
        if self._m_queue is None:
            return
        try:
            self._m_queue.set(stats["queue_depth"])
            self._m_running.set(stats["running"])
            self._m_kv.set(stats["kv_utilization"])
            self._m_tps.set(stats["tokens_per_s"])
            if stats["step_tokens"]:
                self._m_tokens.inc(stats["step_tokens"])
            if stats["step_preemptions"]:
                self._m_preempt.inc(stats["step_preemptions"])
            for t in stats["step_ttfts"]:
                self._m_ttft.observe(t)
            for t in stats["step_tpots"]:
                self._m_tpot.observe(t)
            for key, stat_key, counter in (
                ("hits", "prefix_cache_hits", self._m_pc_hits),
                ("misses", "prefix_cache_misses", self._m_pc_misses),
                ("evictions", "prefix_cache_evictions", self._m_pc_evict),
                ("host_hits", "host_tier_hits", self._m_host_hits),
                ("imported", "blocks_imported", self._m_kv_import),
                ("exported", "blocks_exported", self._m_kv_export),
            ):
                delta = stats[stat_key] - self._kv_exported[key]
                if delta > 0:
                    counter.inc(delta)
                    self._kv_exported[key] += delta
            self._m_host_bytes.set(stats["host_tier_bytes"])
            if stats["step_budget_tokens"]:
                self._m_step_tokens.observe(stats["step_budget_tokens"])
            if stats["step_spec_proposed"]:
                self._m_spec_prop.inc(stats["step_spec_proposed"])
            if stats["step_spec_accepted"]:
                self._m_spec_acc.inc(stats["step_spec_accepted"])
        except Exception:  # noqa: BLE001 — no runtime in unit tests
            pass

    # -------------------------------------------------------------- intake
    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        request_id: Optional[str] = None,
        eos_token: Optional[int] = None,
    ) -> str:
        """Enqueue a request; returns its id immediately. Raises ValueError
        for requests that could NEVER run (too long for the model window or
        the whole KV pool) — transient fullness just queues."""
        if self._stop.is_set():
            raise RuntimeError("engine is shut down")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_seq {self.cfg.max_seq}"
            )
        if not self.block_manager.fits_ever(len(prompt) + max_new_tokens):
            raise ValueError(
                f"request needs {len(prompt) + max_new_tokens} KV slots; pool "
                f"holds {(self.opts.num_blocks - 1) * self.opts.block_size}"
            )
        try:
            from ...util.tracing import get_trace_id

            trace_id = get_trace_id()
        except Exception:  # noqa: BLE001
            trace_id = None
        with self._work:
            if request_id is None:
                request_id = f"req-{self._next_id}"
                self._next_id += 1
            seq = Sequence(
                request_id=request_id,
                prompt=prompt,
                max_new_tokens=max_new_tokens,
                eos_token=eos_token,
            )
            self.scheduler.add(seq)
            self._outputs[request_id] = RequestOutput(request_id)
            if trace_id:
                self._trace_info[request_id] = {
                    "trace": trace_id, "submit_t": time.time(),
                }
            self._work.notify_all()
        return request_id

    def stream(self, request_id: str) -> RequestOutput:
        """Claim a request's output stream (single consumer). Valid until
        claimed no matter how fast the request finished; unknown/already-
        claimed ids raise KeyError."""
        with self._lock:
            out = self._outputs[request_id]
            out.retrieved = True
            if out.finished:
                del self._outputs[request_id]
            return out

    def generate(
        self,
        prompt: List[int],
        max_new_tokens: int,
        eos_token: Optional[int] = None,
    ) -> List[int]:
        """Blocking convenience: submit + drain (the driver thread must be
        running — `start()` — or another thread must call `step()`)."""
        rid = self.submit(prompt, max_new_tokens, eos_token=eos_token)
        return list(self.stream(rid))

    # ---------------------------------------------------------------- step
    def _sample(self, logits_row) -> int:
        if self.opts.temperature <= 0.0:
            return int(logits_row.argmax())
        z = logits_row / self.opts.temperature
        z = z - z.max()
        p = self._np.exp(z)
        p /= p.sum()
        return int(self._sample_rng.choice(len(p), p=p))

    def _emit(self, seq: Sequence, tok: int):
        seq.append_token(tok)
        out = self._outputs.get(seq.request_id)
        if out is not None:
            out._q.put(tok)
        self.total_tokens += 1
        self._tok_window.append(time.monotonic())

    def _maybe_finish(self, seq: Sequence) -> bool:
        reason = seq.should_stop()
        if reason is None:
            return False
        with self._lock:
            self.scheduler.finish(seq, reason)
            out = self._outputs.get(seq.request_id)
            if out is not None:
                out.finish_reason = reason
                out.finished = True
                if out.retrieved:
                    del self._outputs[seq.request_id]
        if out is not None:
            out._q.put(_FINISH)
        self.total_finished += 1
        if seq.first_token_t is not None:
            ttft = seq.first_token_t - seq.arrival_t
            self._ttfts.append(ttft)
            self._step_ttfts.append(ttft)
            n = seq.num_generated  # survives preemption's output fold
            if n > 1 and seq.finish_t is not None:
                tpot = (seq.finish_t - seq.first_token_t) / (n - 1)
                self._tpots.append(tpot)
                self._step_tpots.append(tpot)
        self._emit_request_spans(seq)
        return True

    def _emit_request_spans(self, seq: Sequence):
        """Ship queue-wait/admission/prefill/first-token/completion spans for
        a finished traced request (one shipment per request)."""
        rec = self._trace_info.pop(seq.request_id, None)
        if rec is None:
            return
        try:
            from ...util.tracing import record_events, span_event

            tid = rec["trace"]
            now = time.time()
            submit = rec["submit_t"]
            admit = rec.get("admit_t", now)
            first = rec.get("first_t", admit)
            attrs = {"request_id": seq.request_id,
                     "tokens": seq.num_generated}
            # One control-plane message for the whole request — per-span
            # sends inside step() would stall the decode loop for every
            # in-flight sequence at high completion rates.
            record_events([
                span_event("engine.queue_wait", submit, admit - submit,
                           trace_id=tid, attrs=attrs),
                span_event("engine.admission", admit, 0.0, trace_id=tid,
                           attrs=attrs),
                span_event("engine.prefill", admit, first - admit,
                           trace_id=tid, attrs=attrs),
                span_event("engine.first_token", first, 0.0, trace_id=tid,
                           attrs=attrs),
                span_event("engine.completion", first, now - first,
                           trace_id=tid,
                           attrs={**attrs, "finish_reason": seq.finish_reason}),
            ])
        except Exception:  # noqa: BLE001 — tracing is never load-bearing
            pass

    def _apply_cow(self):
        """Land queued copy-on-write block copies (shared block forked by
        the scheduler) on the physical KV arrays before any kernel reads
        them. Rare — only fork-shared partial blocks ever trigger it."""
        copies = self.block_manager.drain_cow()
        if not copies:
            return
        jnp = self._jnp
        src = jnp.asarray([s for s, _ in copies])
        dst = jnp.asarray([d for _, d in copies])
        self.kv = {
            name: arr.at[:, dst].set(arr[:, src])
            for name, arr in self.kv.items()
        }

    # -------------------------------------------- tiered KV / KV transfer
    #
    # Step-top drain order is a correctness contract (kv_manager header):
    # SAVES read evicted blocks' HBM bytes before anything overwrites them,
    # then COW copies, then LOADS land tier/import bytes, then kernels run.
    # Everything below executes on the driver thread only.

    def _block_blobs(self, blocks: List[int]):
        """The given blocks' KV bytes as contiguous host arrays [2(k/v),
        L, H, BS, Dh] each — the unit of the host tier and the transfer
        plane. Batched: ONE device read per KV array (then per-block host
        copies), not two blocking transfers per block — saves/exports sit
        at the top of the hot step path."""
        np = self._np
        jdx = self._jnp.asarray(blocks)
        ks = np.asarray(self.kv["k"][:, jdx])   # [L, n, H, BS, Dh]
        vs = np.asarray(self.kv["v"][:, jdx])
        return [
            np.ascontiguousarray(np.stack([ks[:, i], vs[:, i]]))
            for i in range(len(blocks))
        ]

    def _apply_host_saves(self):
        """Copy evicted registered blocks' bytes into the host tier (FIRST
        drain: the blocks are already reallocated, and COW/loads/kernels
        may overwrite them later this step)."""
        with self._lock:
            saves = self.block_manager.drain_saves()
        if not saves or self.host_tier is None:
            return
        blobs = self._block_blobs([b for _, b in saves])
        with self._lock:
            for (h, _), blob in zip(saves, blobs):
                self.host_tier.put(h, blob)

    def _apply_host_loads(self):
        """Land tier-hit and imported block bytes on the HBM arrays before
        any kernel reads them (after saves + COW)."""
        with self._lock:
            loads = self.block_manager.drain_loads()
        if not loads:
            return
        jnp = self._jnp
        np = self._np
        idx = jnp.asarray([b for _, b, _, _ in loads])
        ks = np.stack([np.asarray(blob[0]) for _, _, blob, _ in loads])
        vs = np.stack([np.asarray(blob[1]) for _, _, blob, _ in loads])
        dt = self.kv["k"].dtype
        self.kv = {
            "k": self.kv["k"].at[:, idx].set(
                jnp.asarray(ks.swapaxes(0, 1), dt)
            ),
            "v": self.kv["v"].at[:, idx].set(
                jnp.asarray(vs.swapaxes(0, 1), dt)
            ),
        }
        # Local host-tier re-admissions are NOT imports (host_hits counts
        # them) — the import counter tracks only remotely-computed blocks.
        self.total_blocks_imported += sum(
            1 for _, _, _, remote in loads if remote
        )

    def _kv_sig(self) -> str:
        """Layout signature guarding imports: block bytes only interchange
        between engines with identical model geometry, block size, and
        dtype."""
        c = self.cfg
        return (
            f"{c.n_layers}:{c.n_heads}:{c.d_head}:{self.opts.block_size}:"
            f"{self._jnp.dtype(c.dtype).str}"
        )

    def prompt_digests(self, prompt: List[int]) -> List[bytes]:
        """Chain digests of EVERY full block of `prompt` (the kv_manager's
        content address). Unlike admission's cacheable cap this includes a
        block ending exactly at the prompt tail — after a completed prefill
        `register_computed` has registered all of them."""
        from .kv_manager import _chain_hash

        bs = self.opts.block_size
        out: List[bytes] = []
        prev = b""
        for i in range(len(prompt) // bs):
            prev = _chain_hash(prev, prompt[i * bs:(i + 1) * bs])
            out.append(prev)
        return out

    def export_prompt_kv(
        self, prompt: List[int], timeout_s: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """Publish `prompt`'s computed full-block KV as a span descriptor
        (kv_transfer.export_descriptor) any replica can import. Runs on the
        driver thread at a step boundary (the only safe point to read the
        donated KV arrays); this caller blocks until serviced. Returns None
        when there is nothing exportable (short prompt, blocks already
        evicted everywhere, engine stopped)."""
        digests = self.prompt_digests(prompt)
        if not digests or self._stop.is_set():
            return None
        from concurrent.futures import Future, TimeoutError as _FutTimeout

        from ...util import flight
        from ...util.tracing import get_trace_id

        # Captured HERE (the replica RPC thread carries the request's task
        # context); _do_export runs on the driver thread, which has none.
        # The trace rides the descriptor so the importing replica's spans
        # join the same x-request-id forest.
        trace = get_trace_id()
        t0 = flight.now_ns()
        fut: "Future" = Future()
        with self._work:
            self._side_work.append(("export", digests, fut))
            self._work.notify_all()
        try:
            desc = fut.result(
                timeout_s if timeout_s is not None
                else self.opts.kv_transfer_timeout_s
            )
        except _FutTimeout:
            return None
        except Exception:  # noqa: BLE001 — export is best-effort: an arena
            # put or controller RPC failing mid-export must degrade the
            # handoff to colocated recompute, not fail the caller's request.
            return None
        if desc is not None:
            if trace:
                desc["trace"] = trace
            flight.record(
                "kv.export", t0, flight.now_ns(), trace=trace,
                lane="serve/engine", flow=f"disagg/{trace}" if trace else None,
                attrs={"blocks": len(desc.get("digests") or ())})
        return desc

    def _do_export(self, digests: List[bytes]) -> Optional[Dict[str, Any]]:
        """Driver-thread half of export_prompt_kv: gather block bytes (HBM
        blocks at a step boundary; host-tier/pending blobs as-is) and store
        them as one span-addressed arena segment. Digests no longer held
        anywhere are dropped from the descriptor — the importer recomputes
        exactly those blocks."""
        from . import kv_transfer

        with self._lock:
            srcs = self.block_manager.export_sources(digests)
        present: List[bytes] = []
        kept: List[Tuple] = []
        for h, src in zip(digests, srcs):
            if src is None:
                # A chain hole makes every later block unreachable to the
                # importer's walk — stop at the first gap.
                break
            present.append(h)
            kept.append(src)
        if not present:
            return None
        hbm_at = [i for i, s in enumerate(kept) if s[0] == "hbm"]
        hbm_blobs = (
            self._block_blobs([kept[i][1] for i in hbm_at]) if hbm_at else []
        )
        blobs: List = [None] * len(kept)
        for i, blob in zip(hbm_at, hbm_blobs):
            blobs[i] = blob
        for i, s in enumerate(kept):
            if s[0] != "hbm":
                blobs[i] = self._np.asarray(s[1])
        desc = kv_transfer.export_descriptor(
            present, blobs, self._kv_sig(), self.opts.block_size
        )
        if desc is not None:
            self.total_blocks_exported += len(present)
        return desc

    def import_blocks(self, desc: Optional[Dict[str, Any]]) -> int:
        """Adopt a remote replica's exported KV blocks into the local cache
        (called from any thread — the replica RPC thread during a handoff).
        Fetches bytes over the fallback ladder (same-node arena read ->
        bulk span pull -> whole-object get), ALL OR NOTHING, then registers
        each block as a cached entry whose bytes the driver thread lands
        before its next kernel. Returns the number adopted; 0 means the
        importer simply recomputes (degraded mode is the pre-disagg path)."""
        if not desc or not self.opts.enable_prefix_caching \
                or self._stop.is_set():
            return 0
        from ...util import flight

        trace = desc.get("trace")
        t0 = flight.now_ns()

        def _span(n: int, needed: int) -> int:
            flight.record(
                "kv.import", t0, flight.now_ns(), trace=trace,
                lane="serve/engine",
                flow=f"disagg/{trace}" if trace else None,
                attrs={"blocks": n, "needed": needed})
            return n

        if desc.get("sig") != self._kv_sig():
            return _span(0, 0)
        from . import kv_transfer

        with self._lock:
            # A digest already registered in HBM OR resident in the local
            # host tier needs no network fetch — allocate_cached serves the
            # tier copy as a host->HBM memcpy at admission.
            needed = [
                h for h in desc.get("digests") or []
                if self.block_manager.holds(bytes.fromhex(h)) is None
                and not (
                    self.host_tier is not None
                    and self.host_tier.contains(bytes.fromhex(h))
                )
            ]
        if not needed:
            return _span(0, 0)
        blobs = kv_transfer.fetch_blocks(
            desc, needed, timeout_s=self.opts.kv_transfer_timeout_s
        )
        if not blobs:
            return _span(0, len(needed))
        n = 0
        with self._lock:
            for hx, blob in blobs:
                h = bytes.fromhex(hx)
                if self.block_manager.holds(h) is not None:
                    # Raced in since `needed` was computed (a concurrent
                    # import of a shared prefix) — skip, keep adopting the
                    # rest: later digests may still be unique to us.
                    continue
                if self.block_manager.adopt_block(h, blob) is None:
                    break  # pool has nothing to give — the rest recompute
                n += 1
        return _span(n, len(needed))

    def _service_side_work(self):
        """Run queued export requests at the step boundary (after loads:
        freshly imported bytes are already exportable onward)."""
        while True:
            with self._lock:
                if not self._side_work:
                    return
                kind, payload, fut = self._side_work.popleft()
            try:
                result = self._do_export(payload) if kind == "export" else None
                fut.set_result(result)
            except Exception as e:  # noqa: BLE001 — fail the waiter, not the loop
                fut.set_exception(e)

    def _run_prefill(self, chunk):
        """One prefill chunk: compute prompt[start : start+n] into the paged
        cache. Only the FINAL chunk samples the first token (TTFT)."""
        seq = chunk.seq
        rec = self._trace_info.get(seq.request_id)
        if rec is not None and "admit_t" not in rec:
            rec["admit_t"] = time.time()
        jnp = self._jnp
        np = self._np
        table = self.block_manager.block_table(seq.request_id)
        L = chunk.num_tokens
        # Same bucketing primitive as the scheduler's decode shapes —
        # agreement between the two is what bounds the XLA program set.
        Sp = _next_pow2(L)
        W = _next_pow2(len(table))
        tokens = np.zeros((1, Sp), np.int32)
        tokens[0, :L] = seq.prompt[chunk.start:chunk.start + L]
        bt = np.zeros((W,), np.int32)
        bt[: len(table)] = table
        logits, self.kv = self._prefill(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(L, jnp.int32),
            jnp.asarray(chunk.start, jnp.int32),
            jnp.asarray(bt),
            self.kv,
            self.cfg,
        )
        seq.num_computed = chunk.start + L
        # The chunk's KV is landed — its newly-FULL blocks are now safe to
        # serve as prefix-cache hits for later prompts. Under the engine
        # lock: registration touches the hot-hash digest that telemetry
        # (`fleet_state`, actor RPC thread) iterates.
        with self._lock:
            self.block_manager.register_computed(
                seq.request_id, seq.prompt, seq.num_computed
            )
        if chunk.last:
            tok = self._sample(np.asarray(logits))
            self._emit(seq, tok)
            if rec is not None:
                rec.setdefault("first_t", time.time())
            self._maybe_finish(seq)

    def _run_verify(self, out: SchedulerOutput):
        """Speculative step: every decode lane rides ONE `verify_step_paged`
        call — lane i scores its current token plus its funded draft (other
        lanes ride along with an empty draft: their slot 0 is exactly a
        plain decode). Greedy acceptance: the longest draft prefix matching
        the model's own argmax is emitted, then one corrective (or, on full
        acceptance, bonus) token — token-for-token identical to plain
        greedy decode, just fewer dispatches."""
        jnp = self._jnp
        np = self._np
        seqs = out.decodes
        B = out.batch_bucket
        W = out.width_bucket
        K1 = self.opts.spec_tokens + 1
        tokens = np.zeros((B, K1), np.int32)
        positions = np.zeros((B,), np.int32)
        valid_len = np.zeros((B,), np.int32)  # 0 for padding lanes
        tables = np.zeros((B, W), np.int32)   # padding lanes -> null block
        lane_drafts: List[List[int]] = []
        for i, seq in enumerate(seqs):
            d = out.drafts.get(seq.request_id, [])
            lane_drafts.append(d)
            tokens[i, 0] = seq.output[-1]
            if d:
                tokens[i, 1:1 + len(d)] = d
            positions[i] = seq.num_tokens - 1
            valid_len[i] = 1 + len(d)
            table = self.block_manager.block_table(seq.request_id)
            tables[i, : len(table)] = table
        logits, self.kv = self._verify(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(valid_len),
            jnp.asarray(tables),
            self.kv,
            self.cfg,
        )
        logits = np.asarray(logits)
        for i, seq in enumerate(seqs):
            d = lane_drafts[i]
            greedy = logits[i].argmax(axis=-1)
            emitted: List[int] = []
            accepted = 0
            for j, dt in enumerate(d):
                g = int(greedy[j])
                if g == dt:
                    emitted.append(dt)
                    accepted += 1
                else:
                    emitted.append(g)  # the corrective token
                    break
            if accepted == len(d):
                emitted.append(int(greedy[len(d)]))  # bonus token
            self.total_spec_proposed += len(d)
            self.total_spec_accepted += accepted
            self._step_spec[0] += len(d)
            self._step_spec[1] += accepted
            for tok in emitted:
                self._emit(seq, tok)
                if self._maybe_finish(seq):
                    # eos mid-span: later landed KV is garbage ABOVE the
                    # watermark — never registered, freed with the seq.
                    break

    def _run_decode(self, out: SchedulerOutput):
        if out.drafts:
            return self._run_verify(out)
        jnp = self._jnp
        np = self._np
        seqs = out.decodes
        B = out.batch_bucket
        W = out.width_bucket
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, W), np.int32)  # padding lanes -> null block
        for i, seq in enumerate(seqs):
            tokens[i] = seq.output[-1]
            positions[i] = seq.num_tokens - 1   # where this token's KV lands
            table = self.block_manager.block_table(seq.request_id)
            tables[i, : len(table)] = table
        logits, self.kv = self._decode(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(tables),
            self.kv,
            self.cfg,
        )
        logits = np.asarray(logits)
        for i, seq in enumerate(seqs):
            self._emit(seq, self._sample(logits[i]))
            self._maybe_finish(seq)

    def step(self) -> Dict[str, Any]:
        """One engine iteration; safe to drive manually (tests) or from the
        driver thread. Returns a stats snapshot."""
        t0 = time.monotonic()
        # Flight-recorder step span: one monotonic_ns read + enabled()
        # check up front; the record itself only happens on steps that did
        # work. Budgeted ≤5% of decode-step time (test_flight_perf_smoke).
        from ...util import flight

        fl_on = flight.enabled()
        t0_ns = time.monotonic_ns() if fl_on else 0
        self._step_ttfts, self._step_tpots = [], []
        self._step_spec = [0, 0]  # [proposed, accepted]
        tok0 = self.total_tokens
        with self._lock:
            out = self.scheduler.schedule()
        self.total_preemptions += len(out.preempted)
        for seq in out.preempted:
            # Recompute preemption re-queues the request: its admission,
            # prefill, and first-token spans restart at the next schedule
            # (keeping first_t would put first_token BEFORE admission).
            rec = self._trace_info.get(seq.request_id)
            if rec is not None:
                rec.pop("admit_t", None)
                rec.pop("first_t", None)
        # Drain order is load-bearing (kv_manager header): eviction SAVES
        # read their blocks' bytes before COW copies or tier/import LOADS
        # can overwrite them, and everything lands before kernels run.
        self._apply_host_saves()
        self._apply_cow()
        self._apply_host_loads()
        self._service_side_work()
        for chunk in out.prefills:
            self._run_prefill(chunk)
        if out.decodes:
            self._run_decode(out)

        now = time.monotonic()
        self._tok_window = [t for t in self._tok_window if now - t <= 10.0]
        kv_stats = self.block_manager.stats()
        stats = {
            "queue_depth": self.scheduler.queue_depth,
            "running": self.scheduler.num_running,
            "kv_utilization": kv_stats.utilization,
            "kv_free_blocks": kv_stats.free_blocks,
            "kv_cached_blocks": kv_stats.cached_blocks,
            "prefix_cache_hits": kv_stats.hits,
            "prefix_cache_misses": kv_stats.misses,
            "prefix_cache_evictions": kv_stats.evictions,
            "host_tier_hits": kv_stats.host_hits,
            "host_tier_bytes": kv_stats.host_bytes,
            "blocks_imported": self.total_blocks_imported,
            "blocks_exported": self.total_blocks_exported,
            "step_budget_tokens": out.step_tokens,
            "tokens_per_s": (
                len(self._tok_window) / max(now - self._tok_window[0], 1e-3)
                if self._tok_window
                else 0.0
            ),
            "step_tokens": self.total_tokens - tok0,
            "step_preemptions": len(out.preempted),
            "step_prefills": len(out.prefills),
            "step_decodes": len(out.decodes),
            "step_spec_proposed": self._step_spec[0],
            "step_spec_accepted": self._step_spec[1],
            "step_ttfts": list(self._step_ttfts),
            "step_tpots": list(self._step_tpots),
            "step_s": now - t0,
        }
        if fl_on and (out.prefills or out.decodes):
            flight.record(
                "engine.step", t0_ns, time.monotonic_ns(),
                lane=f"serve/engine-{self.opts.role or 'colocated'}",
                attrs={"prefills": len(out.prefills),
                       "decodes": len(out.decodes),
                       "tokens": stats["step_tokens"]})
        self._export_metrics(stats)
        return stats

    def stats(self, include_raw: bool = False) -> Dict[str, Any]:
        """Engine counters + latency summaries. `include_raw=True` adds the
        bounded raw TTFT/TPOT windows so a fleet bench can pool percentiles
        ACROSS replicas instead of averaging per-replica medians."""
        np = self._np
        # Under the engine lock: called from actor RPC threads while the
        # driver thread mutates the block manager (same race fleet_state
        # guards against — _evictable() iterates the cached dict).
        with self._lock:
            kv_stats = self.block_manager.stats()
            ttfts = list(self._ttfts)
            tpots = list(self._tpots)
        extra = (
            {"ttft_recent": ttfts, "tpot_recent": tpots} if include_raw else {}
        )
        return {
            **extra,
            "queue_depth": self.scheduler.queue_depth,
            "running": self.scheduler.num_running,
            "kv_utilization": kv_stats.utilization,
            "kv_cached_blocks": kv_stats.cached_blocks,
            "prefix_cache_hits": kv_stats.hits,
            "prefix_cache_misses": kv_stats.misses,
            "prefix_cache_evictions": kv_stats.evictions,
            "role": self.opts.role,
            "host_tier_hits": kv_stats.host_hits,
            "host_tier_blocks": kv_stats.host_blocks,
            "host_tier_bytes": kv_stats.host_bytes,
            "blocks_imported": self.total_blocks_imported,
            "blocks_exported": self.total_blocks_exported,
            "total_tokens": self.total_tokens,
            "total_finished": self.total_finished,
            "total_preemptions": self.total_preemptions,
            "spec_proposed": self.total_spec_proposed,
            "spec_accepted": self.total_spec_accepted,
            "spec_acceptance_rate": (
                round(self.total_spec_accepted / self.total_spec_proposed, 4)
                if self.total_spec_proposed
                else None
            ),
            "ttft_p50_s": float(np.median(ttfts)) if ttfts else None,
            "ttft_p99_s": _quantile(ttfts, 0.99),
            "tpot_p50_s": float(np.median(tpots)) if tpots else None,
        }

    def fleet_state(self) -> Dict[str, Any]:
        """Bounded telemetry the controller piggybacks on its health probes
        and routers steer by (`serve/fleet/`): load (queue/running/free
        blocks), the hot-prefix digest, the TTFT tail, the RECENT prefix-
        hit rate (30s window — the autoscaler's cache-cold signal), and the
        spec-decode acceptance rate."""
        # Under the engine lock: telemetry runs on the actor RPC thread
        # while the driver thread mutates the block manager (the digest's
        # hot-hash OrderedDict would otherwise be iterated mid-mutation).
        with self._lock:
            kv_stats = self.block_manager.stats()
            digest = self.block_manager.prefix_digest(64)
            queue_depth = self.scheduler.queue_depth
            running = self.scheduler.num_running
            ttfts = list(self._ttfts)
        now = time.monotonic()
        self._hit_snaps.append((now, kv_stats.hits, kv_stats.misses))
        while self._hit_snaps and now - self._hit_snaps[0][0] > 30.0:
            self._hit_snaps.popleft()
        t0, h0, m0 = self._hit_snaps[0]
        dh, dm = kv_stats.hits - h0, kv_stats.misses - m0
        return {
            "queue_depth": queue_depth,
            "running": running,
            "free_blocks": kv_stats.free_blocks,
            "block_size": self.opts.block_size,
            "kv_utilization": kv_stats.utilization,
            "digest": digest,
            # Disaggregated pools: the fleet router splits replicas into
            # prefill/decode pools on this, and the controller autoscales
            # the two pools on their own signals.
            "role": self.opts.role,
            "host_tier_hits": kv_stats.host_hits,
            "host_tier_blocks": kv_stats.host_blocks,
            "host_tier_bytes": kv_stats.host_bytes,
            "ttft_p99_s": _quantile(ttfts, 0.99),
            "prefix_hit_rate": (
                round(dh / (dh + dm), 4) if (dh + dm) > 0 else None
            ),
            "spec_acceptance_rate": (
                round(self.total_spec_accepted / self.total_spec_proposed, 4)
                if self.total_spec_proposed
                else None
            ),
        }

    # -------------------------------------------------------- driver thread
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="llm-engine"
        )
        self._thread.start()

    def shutdown(self):
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # Fail every open stream — a consumer blocked in queue.get() would
        # otherwise hang forever once the driver thread is gone.
        with self._lock:
            outs = list(self._outputs.values())
            self._outputs.clear()
            self._trace_info.clear()
            side, self._side_work = list(self._side_work), deque()
        for out in outs:
            out._q.put(RuntimeError("engine shut down"))
        for _, _, fut in side:
            # Exporters blocked in export_prompt_kv must not wait out their
            # full transfer deadline on a dead driver thread.
            try:
                fut.set_result(None)
            except Exception:  # noqa: BLE001
                pass

    def _loop(self):
        while not self._stop.is_set():
            with self._work:
                while (
                    not self.scheduler.has_work()
                    and not self._side_work
                    and not self._stop.is_set()
                ):
                    self._work.wait(timeout=0.1)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail every open stream
                with self._lock:
                    outs = list(self._outputs.values())
                    self._outputs.clear()
                    self._trace_info.clear()
                    # Drop all scheduler state: without it the loop would
                    # respin on the same poisoned batch forever.
                    for seq in list(self.scheduler.running):
                        self.scheduler.finish(seq, "error")
                    self.scheduler.waiting.clear()
                    self.scheduler._seqs.clear()
                for out in outs:
                    out._q.put(e)
