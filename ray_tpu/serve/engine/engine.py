"""Continuous-batching inference engine (reference-era analog: vLLM's
`LLMEngine.step()` loop — Orca-style iteration-level scheduling over a
PagedAttention cache, here driving `models/gpt.py`'s paged decode path).

One `step()` is one model iteration:

    1. `Scheduler.schedule()` re-forms the working set — admits queued
       prompts the moment the KV budget (free + reclaimable cached blocks)
       covers them, preempts on exhaustion (finished sequences were already
       retired and their blocks freed at the END of the previous step).
       Admission allocates by PREFIX-CACHE lookup first: a prompt whose
       leading full blocks are already resident skips straight to the first
       cold token.
    2. Prefill advances in CHUNKS under a per-step token budget (one jitted
       program per (chunk, width) bucket): each step lands at most
       `prefill_chunk_tokens` of one prompt, so a long prompt never stalls
       the decode streams for a monolithic prefill. The final chunk emits
       the first token — that's TTFT, decoupled from everything else in
       flight.
    3. All fully-prefilled sequences advance one token through ONE jitted
       `decode_step_paged` call — batch padded to a power-of-two lane
       bucket and block-table width bucket, so XLA compiles a bounded set
       of programs no matter how the working set churns.
    4. New tokens stream to per-request output queues; sequences hitting
       their stop condition retire immediately, returning their blocks for
       the NEXT step's admissions.

The engine owns a dedicated driver thread (all JAX compute on one thread);
`submit()`/`stream()` are called from any thread — replica actor method
threads under Serve (`LLMDeployment` runs with max_concurrency > 1 so a
blocked `generate` never gates another request's `submit`).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from collections import deque

from ...util.metrics import quantile as _quantile
from .kv_manager import KVBlockManager
from .scheduler import Scheduler, Sequence, SchedulerOutput, _next_pow2

_FINISH = object()  # stream sentinel

# Jitted paged kernels are process-wide singletons: every engine (and every
# replica in local-mode tests) shares one XLA program cache, keyed by the
# (cfg, shape-bucket) signature jax.jit already tracks. Re-wrapping per
# engine would recompile identical programs per instance.
_JITS = None


def _paged_jits():
    global _JITS
    if _JITS is None:
        import jax

        from ...models.gpt import (
            decode_step_paged,
            prefill_paged,
            verify_step_paged,
        )

        _JITS = (
            jax.jit(prefill_paged, static_argnums=(6,), donate_argnums=(5,)),
            jax.jit(decode_step_paged, static_argnums=(5,), donate_argnums=(4,)),
            jax.jit(verify_step_paged, static_argnums=(6,), donate_argnums=(5,)),
        )
    return _JITS


@dataclasses.dataclass(frozen=True)
class EngineOptions:
    num_blocks: int = 64          # physical KV blocks (incl. null block 0)
    block_size: int = 16          # token slots per block
    max_num_seqs: int = 8         # decode-batch lane ceiling
    max_prefills_per_step: int = 1
    # Chunked prefill: per-step token budget (decode lanes cost 1 each,
    # prefill chunks spend the rest) and the per-chunk length cap — a long
    # prompt lands `prefill_chunk_tokens` per step instead of stalling every
    # decode stream for one monolithic prefill.
    max_step_tokens: int = 256
    prefill_chunk_tokens: int = 64
    # Automatic prefix caching: full KV blocks are content-hashed and
    # shared; a prompt whose prefix is cached skips straight to the first
    # cold block. Freed blocks are retained (reclaimable, LRU-evicted).
    enable_prefix_caching: bool = True
    # Speculative decoding (greedy only): per-lane draft length k proposed
    # by n-gram prompt lookup (spec.py) and scored in ONE verify forward
    # (`verify_step_paged`) — up to k+1 tokens emitted per step per lane.
    # 0 disables. Draft tokens are funded inside `max_step_tokens`.
    spec_tokens: int = 0
    spec_ngram: int = 2
    temperature: float = 0.0      # 0 = greedy
    seed: int = 0


class RequestOutput:
    """Per-request stream endpoint: the engine thread feeds it, any
    consumer thread drains it."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._q: "queue.Queue" = queue.Queue()
        self.finish_reason: Optional[str] = None
        # Registry-cleanup handshake (under the engine lock): the engine
        # drops the registry entry once the request is BOTH finished and
        # retrieved, whichever happens first — a fast request may finish
        # before its caller ever reaches stream().
        self.finished = False
        self.retrieved = False

    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _FINISH:
                return
            if isinstance(item, Exception):
                raise item
            yield item


class InferenceEngine:
    def __init__(
        self,
        cfg,
        params=None,
        options: Optional[EngineOptions] = None,
    ):
        import jax

        from ...models.gpt import init_paged_cache, init_params

        self.cfg = dataclasses.replace(cfg, remat=False, remat_policy=None)
        self.opts = options or EngineOptions()
        self._jnp = jax.numpy
        if params is None:
            params = init_params(jax.random.PRNGKey(self.opts.seed), cfg)
        self.params = params
        self.kv = init_paged_cache(
            self.cfg, self.opts.num_blocks, self.opts.block_size
        )
        self.block_manager = KVBlockManager(
            self.opts.num_blocks,
            self.opts.block_size,
            enable_prefix_caching=self.opts.enable_prefix_caching,
        )
        proposer = None
        if self.opts.spec_tokens > 0:
            if self.opts.temperature > 0.0:
                # The greedy accept rule (longest matching draft prefix +
                # one corrective token) only reproduces GREEDY decode;
                # sampled decode would need rejection sampling.
                raise ValueError(
                    "speculative decoding requires temperature=0 (greedy)"
                )
            from .spec import NGramProposer

            proposer = NGramProposer(
                k=self.opts.spec_tokens, n=self.opts.spec_ngram
            )
        self.scheduler = Scheduler(
            self.block_manager,
            max_num_seqs=self.opts.max_num_seqs,
            max_prefills_per_step=self.opts.max_prefills_per_step,
            max_step_tokens=self.opts.max_step_tokens,
            prefill_chunk=self.opts.prefill_chunk_tokens,
            draft_proposer=proposer,
        )
        # cfg is static (hashable frozen dataclass); kv buffers are donated
        # — each call consumes self.kv and hands back its successor.
        self._prefill, self._decode, self._verify = _paged_jits()
        import numpy as np

        self._np = np
        self._sample_rng = np.random.default_rng(self.opts.seed)
        self._lock = threading.Lock()          # scheduler + queues
        self._work = threading.Condition(self._lock)
        self._outputs: Dict[str, RequestOutput] = {}
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Rolling throughput/latency accounting (host-side, cheap). The
        # latency windows are bounded — a long-lived replica must not
        # accumulate one float per request forever.
        self.total_tokens = 0
        self.total_preemptions = 0
        self.total_finished = 0
        self.total_spec_proposed = 0
        self.total_spec_accepted = 0
        self._ttfts: "deque[float]" = deque(maxlen=1024)
        self._tpots: "deque[float]" = deque(maxlen=1024)
        self._step_ttfts: List[float] = []     # reset each step()
        self._step_tpots: List[float] = []
        self._step_spec = [0, 0]               # [proposed, accepted]
        self._tok_window: List[float] = []     # token-emit timestamps
        # (t, hits, misses) snapshots — fleet_state's RECENT hit-rate
        # window, the autoscaler's cache-cold signal.
        self._hit_snaps: "deque" = deque(maxlen=64)
        # request_id -> {trace, submit_t, admit_t, first_t} (wall-clock):
        # per-request span bookkeeping for traced (Serve) submissions —
        # untraced submits (engine unit tests, direct callers) skip it.
        self._trace_info: Dict[str, Dict[str, Any]] = {}
        self._init_metrics()

    # ------------------------------------------------------------- metrics
    def _init_metrics(self):
        try:
            from ...util.metrics import Counter, Gauge, Histogram

            self._m_queue = Gauge(
                "serve_engine_queue_depth", "prompts waiting for KV admission"
            )
            self._m_running = Gauge(
                "serve_engine_running_seqs", "sequences in the decode batch"
            )
            self._m_kv = Gauge(
                "serve_engine_kv_utilization", "allocated fraction of KV blocks"
            )
            self._m_tps = Gauge(
                "serve_engine_tokens_per_s", "generated tokens/s (10s window)"
            )
            self._m_tokens = Counter(
                "serve_engine_tokens_total", "tokens generated"
            )
            self._m_preempt = Counter(
                "serve_engine_preemptions_total", "recompute preemptions"
            )
            self._m_ttft = Histogram(
                "serve_engine_ttft_s", "time to first token"
            )
            self._m_tpot = Histogram(
                "serve_engine_tpot_s", "time per output token after the first"
            )
            self._m_pc_hits = Counter(
                "serve_engine_prefix_cache_hits_total",
                "KV blocks served from the prefix cache",
            )
            self._m_pc_misses = Counter(
                "serve_engine_prefix_cache_misses_total",
                "cacheable KV blocks that had to be computed",
            )
            self._m_pc_evict = Counter(
                "serve_engine_prefix_cache_evictions_total",
                "cached KV blocks reclaimed for new allocations",
            )
            self._m_step_tokens = Histogram(
                "serve_engine_step_budget_tokens",
                "tokens scheduled per engine step "
                "(decode lanes + prefill chunk tokens)",
                boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            )
            self._m_spec_prop = Counter(
                "serve_engine_spec_proposed_total",
                "speculative draft tokens scored by the verify step",
            )
            self._m_spec_acc = Counter(
                "serve_engine_spec_accepted_total",
                "speculative draft tokens accepted (emitted without a "
                "dedicated decode step)",
            )
            # Counters export monotonic increments; the KV manager keeps
            # lifetime totals — ship deltas since the last step.
            self._kv_exported = {"hits": 0, "misses": 0, "evictions": 0}
            try:
                # Under Serve, tag every series with its replica so scrapes
                # distinguish replicas and the controller can prune a
                # drained replica's series (serve/controller._drain).
                from ..context import get_replica_context

                ctx = get_replica_context()
                tags = {"app": ctx.app_name, "deployment": ctx.deployment,
                        "replica": ctx.replica_tag}
                for m in (self._m_queue, self._m_running, self._m_kv,
                          self._m_tps, self._m_tokens, self._m_preempt,
                          self._m_ttft, self._m_tpot, self._m_pc_hits,
                          self._m_pc_misses, self._m_pc_evict,
                          self._m_step_tokens, self._m_spec_prop,
                          self._m_spec_acc):
                    m.set_default_tags(tags)
            except Exception:  # noqa: BLE001 — engine used outside Serve
                pass
        except Exception:  # noqa: BLE001 — metrics are never load-bearing
            self._m_queue = None

    def _export_metrics(self, stats: Dict[str, Any]):
        if self._m_queue is None:
            return
        try:
            self._m_queue.set(stats["queue_depth"])
            self._m_running.set(stats["running"])
            self._m_kv.set(stats["kv_utilization"])
            self._m_tps.set(stats["tokens_per_s"])
            if stats["step_tokens"]:
                self._m_tokens.inc(stats["step_tokens"])
            if stats["step_preemptions"]:
                self._m_preempt.inc(stats["step_preemptions"])
            for t in stats["step_ttfts"]:
                self._m_ttft.observe(t)
            for t in stats["step_tpots"]:
                self._m_tpot.observe(t)
            for key, counter in (
                ("hits", self._m_pc_hits),
                ("misses", self._m_pc_misses),
                ("evictions", self._m_pc_evict),
            ):
                delta = stats[f"prefix_cache_{key}"] - self._kv_exported[key]
                if delta > 0:
                    counter.inc(delta)
                    self._kv_exported[key] += delta
            if stats["step_budget_tokens"]:
                self._m_step_tokens.observe(stats["step_budget_tokens"])
            if stats["step_spec_proposed"]:
                self._m_spec_prop.inc(stats["step_spec_proposed"])
            if stats["step_spec_accepted"]:
                self._m_spec_acc.inc(stats["step_spec_accepted"])
        except Exception:  # noqa: BLE001 — no runtime in unit tests
            pass

    # -------------------------------------------------------------- intake
    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        request_id: Optional[str] = None,
        eos_token: Optional[int] = None,
    ) -> str:
        """Enqueue a request; returns its id immediately. Raises ValueError
        for requests that could NEVER run (too long for the model window or
        the whole KV pool) — transient fullness just queues."""
        if self._stop.is_set():
            raise RuntimeError("engine is shut down")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_seq {self.cfg.max_seq}"
            )
        if not self.block_manager.fits_ever(len(prompt) + max_new_tokens):
            raise ValueError(
                f"request needs {len(prompt) + max_new_tokens} KV slots; pool "
                f"holds {(self.opts.num_blocks - 1) * self.opts.block_size}"
            )
        try:
            from ...util.tracing import get_trace_id

            trace_id = get_trace_id()
        except Exception:  # noqa: BLE001
            trace_id = None
        with self._work:
            if request_id is None:
                request_id = f"req-{self._next_id}"
                self._next_id += 1
            seq = Sequence(
                request_id=request_id,
                prompt=prompt,
                max_new_tokens=max_new_tokens,
                eos_token=eos_token,
            )
            self.scheduler.add(seq)
            self._outputs[request_id] = RequestOutput(request_id)
            if trace_id:
                self._trace_info[request_id] = {
                    "trace": trace_id, "submit_t": time.time(),
                }
            self._work.notify_all()
        return request_id

    def stream(self, request_id: str) -> RequestOutput:
        """Claim a request's output stream (single consumer). Valid until
        claimed no matter how fast the request finished; unknown/already-
        claimed ids raise KeyError."""
        with self._lock:
            out = self._outputs[request_id]
            out.retrieved = True
            if out.finished:
                del self._outputs[request_id]
            return out

    def generate(
        self,
        prompt: List[int],
        max_new_tokens: int,
        eos_token: Optional[int] = None,
    ) -> List[int]:
        """Blocking convenience: submit + drain (the driver thread must be
        running — `start()` — or another thread must call `step()`)."""
        rid = self.submit(prompt, max_new_tokens, eos_token=eos_token)
        return list(self.stream(rid))

    # ---------------------------------------------------------------- step
    def _sample(self, logits_row) -> int:
        if self.opts.temperature <= 0.0:
            return int(logits_row.argmax())
        z = logits_row / self.opts.temperature
        z = z - z.max()
        p = self._np.exp(z)
        p /= p.sum()
        return int(self._sample_rng.choice(len(p), p=p))

    def _emit(self, seq: Sequence, tok: int):
        seq.append_token(tok)
        out = self._outputs.get(seq.request_id)
        if out is not None:
            out._q.put(tok)
        self.total_tokens += 1
        self._tok_window.append(time.monotonic())

    def _maybe_finish(self, seq: Sequence) -> bool:
        reason = seq.should_stop()
        if reason is None:
            return False
        with self._lock:
            self.scheduler.finish(seq, reason)
            out = self._outputs.get(seq.request_id)
            if out is not None:
                out.finish_reason = reason
                out.finished = True
                if out.retrieved:
                    del self._outputs[seq.request_id]
        if out is not None:
            out._q.put(_FINISH)
        self.total_finished += 1
        if seq.first_token_t is not None:
            ttft = seq.first_token_t - seq.arrival_t
            self._ttfts.append(ttft)
            self._step_ttfts.append(ttft)
            n = seq.num_generated  # survives preemption's output fold
            if n > 1 and seq.finish_t is not None:
                tpot = (seq.finish_t - seq.first_token_t) / (n - 1)
                self._tpots.append(tpot)
                self._step_tpots.append(tpot)
        self._emit_request_spans(seq)
        return True

    def _emit_request_spans(self, seq: Sequence):
        """Ship queue-wait/admission/prefill/first-token/completion spans for
        a finished traced request (one shipment per request)."""
        rec = self._trace_info.pop(seq.request_id, None)
        if rec is None:
            return
        try:
            from ...util.tracing import record_events, span_event

            tid = rec["trace"]
            now = time.time()
            submit = rec["submit_t"]
            admit = rec.get("admit_t", now)
            first = rec.get("first_t", admit)
            attrs = {"request_id": seq.request_id,
                     "tokens": seq.num_generated}
            # One control-plane message for the whole request — per-span
            # sends inside step() would stall the decode loop for every
            # in-flight sequence at high completion rates.
            record_events([
                span_event("engine.queue_wait", submit, admit - submit,
                           trace_id=tid, attrs=attrs),
                span_event("engine.admission", admit, 0.0, trace_id=tid,
                           attrs=attrs),
                span_event("engine.prefill", admit, first - admit,
                           trace_id=tid, attrs=attrs),
                span_event("engine.first_token", first, 0.0, trace_id=tid,
                           attrs=attrs),
                span_event("engine.completion", first, now - first,
                           trace_id=tid,
                           attrs={**attrs, "finish_reason": seq.finish_reason}),
            ])
        except Exception:  # noqa: BLE001 — tracing is never load-bearing
            pass

    def _apply_cow(self):
        """Land queued copy-on-write block copies (shared block forked by
        the scheduler) on the physical KV arrays before any kernel reads
        them. Rare — only fork-shared partial blocks ever trigger it."""
        copies = self.block_manager.drain_cow()
        if not copies:
            return
        jnp = self._jnp
        src = jnp.asarray([s for s, _ in copies])
        dst = jnp.asarray([d for _, d in copies])
        self.kv = {
            name: arr.at[:, dst].set(arr[:, src])
            for name, arr in self.kv.items()
        }

    def _run_prefill(self, chunk):
        """One prefill chunk: compute prompt[start : start+n] into the paged
        cache. Only the FINAL chunk samples the first token (TTFT)."""
        seq = chunk.seq
        rec = self._trace_info.get(seq.request_id)
        if rec is not None and "admit_t" not in rec:
            rec["admit_t"] = time.time()
        jnp = self._jnp
        np = self._np
        table = self.block_manager.block_table(seq.request_id)
        L = chunk.num_tokens
        # Same bucketing primitive as the scheduler's decode shapes —
        # agreement between the two is what bounds the XLA program set.
        Sp = _next_pow2(L)
        W = _next_pow2(len(table))
        tokens = np.zeros((1, Sp), np.int32)
        tokens[0, :L] = seq.prompt[chunk.start:chunk.start + L]
        bt = np.zeros((W,), np.int32)
        bt[: len(table)] = table
        logits, self.kv = self._prefill(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(L, jnp.int32),
            jnp.asarray(chunk.start, jnp.int32),
            jnp.asarray(bt),
            self.kv,
            self.cfg,
        )
        seq.num_computed = chunk.start + L
        # The chunk's KV is landed — its newly-FULL blocks are now safe to
        # serve as prefix-cache hits for later prompts. Under the engine
        # lock: registration touches the hot-hash digest that telemetry
        # (`fleet_state`, actor RPC thread) iterates.
        with self._lock:
            self.block_manager.register_computed(
                seq.request_id, seq.prompt, seq.num_computed
            )
        if chunk.last:
            tok = self._sample(np.asarray(logits))
            self._emit(seq, tok)
            if rec is not None:
                rec.setdefault("first_t", time.time())
            self._maybe_finish(seq)

    def _run_verify(self, out: SchedulerOutput):
        """Speculative step: every decode lane rides ONE `verify_step_paged`
        call — lane i scores its current token plus its funded draft (other
        lanes ride along with an empty draft: their slot 0 is exactly a
        plain decode). Greedy acceptance: the longest draft prefix matching
        the model's own argmax is emitted, then one corrective (or, on full
        acceptance, bonus) token — token-for-token identical to plain
        greedy decode, just fewer dispatches."""
        jnp = self._jnp
        np = self._np
        seqs = out.decodes
        B = out.batch_bucket
        W = out.width_bucket
        K1 = self.opts.spec_tokens + 1
        tokens = np.zeros((B, K1), np.int32)
        positions = np.zeros((B,), np.int32)
        valid_len = np.zeros((B,), np.int32)  # 0 for padding lanes
        tables = np.zeros((B, W), np.int32)   # padding lanes -> null block
        lane_drafts: List[List[int]] = []
        for i, seq in enumerate(seqs):
            d = out.drafts.get(seq.request_id, [])
            lane_drafts.append(d)
            tokens[i, 0] = seq.output[-1]
            if d:
                tokens[i, 1:1 + len(d)] = d
            positions[i] = seq.num_tokens - 1
            valid_len[i] = 1 + len(d)
            table = self.block_manager.block_table(seq.request_id)
            tables[i, : len(table)] = table
        logits, self.kv = self._verify(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(valid_len),
            jnp.asarray(tables),
            self.kv,
            self.cfg,
        )
        logits = np.asarray(logits)
        for i, seq in enumerate(seqs):
            d = lane_drafts[i]
            greedy = logits[i].argmax(axis=-1)
            emitted: List[int] = []
            accepted = 0
            for j, dt in enumerate(d):
                g = int(greedy[j])
                if g == dt:
                    emitted.append(dt)
                    accepted += 1
                else:
                    emitted.append(g)  # the corrective token
                    break
            if accepted == len(d):
                emitted.append(int(greedy[len(d)]))  # bonus token
            self.total_spec_proposed += len(d)
            self.total_spec_accepted += accepted
            self._step_spec[0] += len(d)
            self._step_spec[1] += accepted
            for tok in emitted:
                self._emit(seq, tok)
                if self._maybe_finish(seq):
                    # eos mid-span: later landed KV is garbage ABOVE the
                    # watermark — never registered, freed with the seq.
                    break

    def _run_decode(self, out: SchedulerOutput):
        if out.drafts:
            return self._run_verify(out)
        jnp = self._jnp
        np = self._np
        seqs = out.decodes
        B = out.batch_bucket
        W = out.width_bucket
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, W), np.int32)  # padding lanes -> null block
        for i, seq in enumerate(seqs):
            tokens[i] = seq.output[-1]
            positions[i] = seq.num_tokens - 1   # where this token's KV lands
            table = self.block_manager.block_table(seq.request_id)
            tables[i, : len(table)] = table
        logits, self.kv = self._decode(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(tables),
            self.kv,
            self.cfg,
        )
        logits = np.asarray(logits)
        for i, seq in enumerate(seqs):
            self._emit(seq, self._sample(logits[i]))
            self._maybe_finish(seq)

    def step(self) -> Dict[str, Any]:
        """One engine iteration; safe to drive manually (tests) or from the
        driver thread. Returns a stats snapshot."""
        t0 = time.monotonic()
        self._step_ttfts, self._step_tpots = [], []
        self._step_spec = [0, 0]  # [proposed, accepted]
        tok0 = self.total_tokens
        with self._lock:
            out = self.scheduler.schedule()
        self.total_preemptions += len(out.preempted)
        for seq in out.preempted:
            # Recompute preemption re-queues the request: its admission,
            # prefill, and first-token spans restart at the next schedule
            # (keeping first_t would put first_token BEFORE admission).
            rec = self._trace_info.get(seq.request_id)
            if rec is not None:
                rec.pop("admit_t", None)
                rec.pop("first_t", None)
        self._apply_cow()
        for chunk in out.prefills:
            self._run_prefill(chunk)
        if out.decodes:
            self._run_decode(out)

        now = time.monotonic()
        self._tok_window = [t for t in self._tok_window if now - t <= 10.0]
        kv_stats = self.block_manager.stats()
        stats = {
            "queue_depth": self.scheduler.queue_depth,
            "running": self.scheduler.num_running,
            "kv_utilization": kv_stats.utilization,
            "kv_free_blocks": kv_stats.free_blocks,
            "kv_cached_blocks": kv_stats.cached_blocks,
            "prefix_cache_hits": kv_stats.hits,
            "prefix_cache_misses": kv_stats.misses,
            "prefix_cache_evictions": kv_stats.evictions,
            "step_budget_tokens": out.step_tokens,
            "tokens_per_s": (
                len(self._tok_window) / max(now - self._tok_window[0], 1e-3)
                if self._tok_window
                else 0.0
            ),
            "step_tokens": self.total_tokens - tok0,
            "step_preemptions": len(out.preempted),
            "step_prefills": len(out.prefills),
            "step_decodes": len(out.decodes),
            "step_spec_proposed": self._step_spec[0],
            "step_spec_accepted": self._step_spec[1],
            "step_ttfts": list(self._step_ttfts),
            "step_tpots": list(self._step_tpots),
            "step_s": now - t0,
        }
        self._export_metrics(stats)
        return stats

    def stats(self, include_raw: bool = False) -> Dict[str, Any]:
        """Engine counters + latency summaries. `include_raw=True` adds the
        bounded raw TTFT/TPOT windows so a fleet bench can pool percentiles
        ACROSS replicas instead of averaging per-replica medians."""
        np = self._np
        # Under the engine lock: called from actor RPC threads while the
        # driver thread mutates the block manager (same race fleet_state
        # guards against — _evictable() iterates the cached dict).
        with self._lock:
            kv_stats = self.block_manager.stats()
            ttfts = list(self._ttfts)
            tpots = list(self._tpots)
        extra = (
            {"ttft_recent": ttfts, "tpot_recent": tpots} if include_raw else {}
        )
        return {
            **extra,
            "queue_depth": self.scheduler.queue_depth,
            "running": self.scheduler.num_running,
            "kv_utilization": kv_stats.utilization,
            "kv_cached_blocks": kv_stats.cached_blocks,
            "prefix_cache_hits": kv_stats.hits,
            "prefix_cache_misses": kv_stats.misses,
            "prefix_cache_evictions": kv_stats.evictions,
            "total_tokens": self.total_tokens,
            "total_finished": self.total_finished,
            "total_preemptions": self.total_preemptions,
            "spec_proposed": self.total_spec_proposed,
            "spec_accepted": self.total_spec_accepted,
            "spec_acceptance_rate": (
                round(self.total_spec_accepted / self.total_spec_proposed, 4)
                if self.total_spec_proposed
                else None
            ),
            "ttft_p50_s": float(np.median(ttfts)) if ttfts else None,
            "ttft_p99_s": _quantile(ttfts, 0.99),
            "tpot_p50_s": float(np.median(tpots)) if tpots else None,
        }

    def fleet_state(self) -> Dict[str, Any]:
        """Bounded telemetry the controller piggybacks on its health probes
        and routers steer by (`serve/fleet/`): load (queue/running/free
        blocks), the hot-prefix digest, the TTFT tail, the RECENT prefix-
        hit rate (30s window — the autoscaler's cache-cold signal), and the
        spec-decode acceptance rate."""
        # Under the engine lock: telemetry runs on the actor RPC thread
        # while the driver thread mutates the block manager (the digest's
        # hot-hash OrderedDict would otherwise be iterated mid-mutation).
        with self._lock:
            kv_stats = self.block_manager.stats()
            digest = self.block_manager.prefix_digest(64)
            queue_depth = self.scheduler.queue_depth
            running = self.scheduler.num_running
            ttfts = list(self._ttfts)
        now = time.monotonic()
        self._hit_snaps.append((now, kv_stats.hits, kv_stats.misses))
        while self._hit_snaps and now - self._hit_snaps[0][0] > 30.0:
            self._hit_snaps.popleft()
        t0, h0, m0 = self._hit_snaps[0]
        dh, dm = kv_stats.hits - h0, kv_stats.misses - m0
        return {
            "queue_depth": queue_depth,
            "running": running,
            "free_blocks": kv_stats.free_blocks,
            "block_size": self.opts.block_size,
            "kv_utilization": kv_stats.utilization,
            "digest": digest,
            "ttft_p99_s": _quantile(ttfts, 0.99),
            "prefix_hit_rate": (
                round(dh / (dh + dm), 4) if (dh + dm) > 0 else None
            ),
            "spec_acceptance_rate": (
                round(self.total_spec_accepted / self.total_spec_proposed, 4)
                if self.total_spec_proposed
                else None
            ),
        }

    # -------------------------------------------------------- driver thread
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="llm-engine"
        )
        self._thread.start()

    def shutdown(self):
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # Fail every open stream — a consumer blocked in queue.get() would
        # otherwise hang forever once the driver thread is gone.
        with self._lock:
            outs = list(self._outputs.values())
            self._outputs.clear()
            self._trace_info.clear()
        for out in outs:
            out._q.put(RuntimeError("engine shut down"))

    def _loop(self):
        while not self._stop.is_set():
            with self._work:
                while not self.scheduler.has_work() and not self._stop.is_set():
                    self._work.wait(timeout=0.1)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail every open stream
                with self._lock:
                    outs = list(self._outputs.values())
                    self._outputs.clear()
                    self._trace_info.clear()
                    # Drop all scheduler state: without it the loop would
                    # respin on the same poisoned batch forever.
                    for seq in list(self.scheduler.running):
                        self.scheduler.finish(seq, "error")
                    self.scheduler.waiting.clear()
                    self.scheduler._seqs.clear()
                for out in outs:
                    out._q.put(e)
