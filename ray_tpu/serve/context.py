"""Replica-side request context (reference: `serve/context.py`
`get_replica_context`, `serve/multiplex.py` `get_multiplexed_model_id`)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

_local = threading.local()


@dataclasses.dataclass
class ReplicaContext:
    app_name: str
    deployment: str
    replica_tag: str


def get_replica_context() -> ReplicaContext:
    ctx = getattr(_local, "replica_context", None)
    if ctx is None:
        raise RuntimeError("get_replica_context() called outside a Serve replica")
    return ctx


def _set_replica_context(ctx: Optional[ReplicaContext]):
    _local.replica_context = ctx


def get_multiplexed_model_id() -> str:
    return getattr(_local, "multiplexed_model_id", "")


def _set_multiplexed_model_id(model_id: str):
    _local.multiplexed_model_id = model_id


def get_request_id() -> str:
    """Id of the Serve request being handled on this thread (assigned per
    HTTP request by the proxy, equal to the request's trace_id — the same
    id keys `/api/traces` and `ray_tpu trace`). Empty outside a request."""
    return getattr(_local, "request_id", "")


def _set_request_id(request_id: str):
    _local.request_id = request_id
