"""Replica actor (reference: `serve/_private/replica.py`).

A generic actor wrapping the user's deployment callable. Requests arrive as
`handle_request(method, args, kwargs)` actor tasks — ordered execution per
replica is exactly the reference's single-asyncio-loop replica semantics.
Batched methods receive the router-formed list in one call.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ..util import tracing
from .context import (
    ReplicaContext,
    _set_multiplexed_model_id,
    _set_replica_context,
    _set_request_id,
)


class Replica:
    """NOTE: instantiated as a ray_tpu actor by the controller."""

    def __init__(
        self,
        app_name: str,
        deployment_name: str,
        replica_tag: str,
        serialized_cls: bytes,
        serialized_init_args: bytes,
        user_config: Optional[dict] = None,
        role: Optional[str] = None,
    ):
        cls = cloudpickle.loads(serialized_cls)
        args, kwargs = cloudpickle.loads(serialized_init_args)
        if role:
            # Disaggregated pools: the controller assigns this replica's
            # engine role (prefill/decode) at start time — merged into the
            # `engine_options` kwarg the LLM deployment class accepts.
            # Only deployments configured with prefill_replicas > 0 ever
            # receive a role, so non-engine classes are never touched.
            kwargs = dict(kwargs)
            kwargs["engine_options"] = {
                **(kwargs.get("engine_options") or {}), "role": role,
            }
        self._role = role
        self._ctx = ReplicaContext(app_name, deployment_name, replica_tag)
        _set_replica_context(self._ctx)
        if isinstance(cls, type):
            self._callable = cls(*args, **kwargs)
            self._is_function = False
        else:
            self._callable = cls
            self._is_function = True
        self._num_processed = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config: dict):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def _enter_request(self) -> str:
        """Adopt the request/trace id the executing worker inherited from
        the submitting context (the HTTP proxy or a Python caller)."""
        rid = tracing.get_trace_id() or ""
        _set_request_id(rid)
        return rid

    def _record_span(self, name: str, rid: str, method: str, t0: float):
        if not rid:
            return  # untraced call (no request context) — keep timeline lean
        try:
            tracing.record_span(
                name, t0, time.time() - t0, trace_id=rid,
                attrs={"app": self._ctx.app_name,
                       "deployment": self._ctx.deployment,
                       "replica": self._ctx.replica_tag,
                       "method": method, "request_id": rid},
            )
        except Exception:  # noqa: BLE001 — tracing is never load-bearing
            pass

    def handle_request(
        self,
        method: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
    ) -> Any:
        _set_replica_context(self._ctx)
        _set_multiplexed_model_id(multiplexed_model_id)
        rid = self._enter_request()
        self._num_processed += 1
        t0 = time.time()
        try:
            if self._is_function:
                return self._callable(*args, **kwargs)
            return getattr(self._callable, method)(*args, **kwargs)
        finally:
            self._record_span("replica.handle", rid, method, t0)

    def handle_request_streaming(
        self,
        method: str,
        args: Tuple,
        kwargs: Dict,
        multiplexed_model_id: str = "",
    ):
        """Generator variant: yields response chunks as the user generator
        produces them (reference: Serve streaming responses /
        `handle.options(stream=True)`). Runs as a streaming actor task."""
        _set_replica_context(self._ctx)
        _set_multiplexed_model_id(multiplexed_model_id)
        rid = self._enter_request()
        self._num_processed += 1
        fn = self._callable if self._is_function else getattr(self._callable, method)
        t0 = time.time()
        out = fn(*args, **kwargs)
        import inspect

        if not inspect.isgenerator(out):
            raise TypeError(
                f"stream=True requires {method} to be a generator function"
            )
        try:
            yield from out
        finally:
            # Span covers the full drain — the generator body runs lazily.
            self._record_span("replica.handle_stream", rid, method, t0)

    def handle_batch(
        self,
        method: str,
        batched_args: List[Any],
        multiplexed_model_id: str = "",
    ) -> List[Any]:
        """Execute a router-formed batch: the user's @serve.batch method gets
        the list of single args and returns a list of results."""
        _set_replica_context(self._ctx)
        _set_multiplexed_model_id(multiplexed_model_id)
        self._num_processed += len(batched_args)
        fn = getattr(self._callable, method)
        results = fn(batched_args)
        if len(results) != len(batched_args):
            raise ValueError(
                f"@serve.batch method {method} returned {len(results)} results "
                f"for {len(batched_args)} inputs"
            )
        return results

    def ping(self) -> str:
        return "ok"

    def telemetry(self) -> Dict[str, Any]:
        """Health probe + piggybacked fleet telemetry in ONE round trip:
        the controller's reconcile loop calls this instead of `ping`, and a
        deployment exposing `fleet_state()` (the LLM engine does) ships its
        hot-prefix digest / queue depth / TTFT tail with every probe — no
        extra RPC, no extra poll loop."""
        out: Dict[str, Any] = {"ok": True, "num_processed": self._num_processed}
        fn = getattr(self._callable, "fleet_state", None)
        if fn is not None:
            try:
                out["engine"] = fn()
            except Exception:  # noqa: BLE001 — telemetry never fails health
                out["engine"] = None
        return out

    def stats(self) -> Dict[str, Any]:
        return {"num_processed": self._num_processed}
