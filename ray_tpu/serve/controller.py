"""ServeController actor (reference: `serve/_private/controller.py:89`,
deployment reconciler `serve/_private/deployment_state.py:1210,2307`,
autoscaling `serve/_private/autoscaling_policy.py`).

Owns the desired state (applications → deployments → target replica counts),
reconciles it against live replica actors, and serves routing info to
routers/proxies. The reference's LongPollHost broadcast becomes versioned
snapshots that routers re-fetch when stale (short-poll at the router's
refresh interval — no blocking calls into the single-threaded controller).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"

# A router that stops reporting for this long no longer counts toward the
# deployment's outstanding-request total (process exited, handle dropped).
_ROUTER_REPORT_TTL_S = 5.0


class _DeploymentState:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.target_replicas: int = spec["opts"]["num_replicas"]
        # Disaggregated pools (opts.prefill_replicas > 0): how many of the
        # target replicas run role="prefill"; the rest run role="decode".
        # Autoscaled separately from the decode pool (_maybe_autoscale).
        self.target_prefill: int = min(
            int(spec["opts"].get("prefill_replicas") or 0),
            max(self.target_replicas - 1, 0),
        )
        self.replica_roles: Dict[str, str] = {}  # tag -> prefill|decode
        self.replicas: List = []  # READY ActorHandles (routable)
        self.replica_tags: List[str] = []
        # Replicas whose __init__ has not answered a ping yet (model load +
        # jit compile can take MINUTES for LLM replicas — the reference's
        # DeploymentState keeps a STARTING state for exactly this;
        # `deployment_state.py:1210`). Not routable, not respawn-eligible
        # until replica_startup_timeout_s.
        self.starting: List = []  # [(handle, tag, started_at)]
        self.next_replica_id = 0
        # Consecutive missed pings per READY replica tag; replaced at 3.
        self.miss_counts: Dict[str, int] = {}
        # autoscaling bookkeeping: outstanding counts are keyed PER ROUTER
        # and summed — EMA-blending different routers' reports into one
        # stream undercounted the fleet (two routers with 10 outstanding
        # each converged the EMA to ~10, not 20).
        self.router_reports: Dict[str, List[float]] = {}  # id -> [ongoing, t]
        self.ongoing_ema: float = 0.0
        self.last_scale_action_t: float = 0.0
        # Latest fleet telemetry per READY replica tag (piggybacked on the
        # reconcile health probe): {"t": mono, "engine": {...} | None}.
        self.replica_meta: Dict[str, Dict[str, Any]] = {}
        self.status: str = "UPDATING"

    def ongoing_total(self, now: float) -> float:
        """Outstanding requests summed across LIVE routers; expired
        reporters are pruned in place."""
        dead = [
            rid for rid, (_, t) in self.router_reports.items()
            if now - t > _ROUTER_REPORT_TTL_S
        ]
        for rid in dead:
            del self.router_reports[rid]
        return sum(v for v, _ in self.router_reports.values())


def _drain_pool_pick(state: _DeploymentState) -> Optional[int]:
    """Index into state.replicas of the next drain victim for a
    DISAGGREGATED deployment: drain from the pool exceeding its target
    (newest first within the pool), so a decode-pool scale-down can
    never eat the prefill pool or vice versa. None = no preference.
    Module-level like the rest of _drain's logic: draining is a pure
    function of `state` (tested that way)."""
    if state.target_prefill <= 0 or not state.replicas:
        return None
    n_prefill = sum(
        1 for t in state.replica_tags
        if state.replica_roles.get(t) == "prefill"
    )
    over = (
        "prefill" if n_prefill > state.target_prefill else "decode"
    )
    for i in range(len(state.replica_tags) - 1, -1, -1):
        if state.replica_roles.get(state.replica_tags[i]) == over:
            return i
    return None


class ServeController:
    def __init__(self):
        self._lock = threading.RLock()          # guards state reads/writes (brief)
        self._reconcile_lock = threading.Lock()  # serializes reconcile passes
        self._apps: Dict[str, Dict[str, Any]] = {}  # app -> {deployments, route_prefix, ingress}
        self._version = 0
        self._shutdown = False
        self._reconciler = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._reconciler.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(
        self,
        app_name: str,
        dep_specs: List[Dict[str, Any]],
        route_prefix: str,
        ingress_name: str,
        ingress_streaming: bool = False,
    ) -> None:
        import ray_tpu

        with self._lock:
            old = self._apps.get(app_name, {"deployments": {}})
            deployments = {}
            reconfigure_refs = []
            for spec in dep_specs:
                name = spec["name"]
                prev = old["deployments"].get(name)
                state = _DeploymentState(spec)
                if prev is not None and prev.spec["cls"] == spec["cls"]:
                    # In-place update: keep live replicas, adopt new targets,
                    # and push the (possibly changed) user_config to them.
                    state.replicas = prev.replicas
                    state.replica_tags = prev.replica_tags
                    state.starting = prev.starting
                    state.miss_counts = prev.miss_counts
                    state.next_replica_id = prev.next_replica_id
                    state.replica_roles = prev.replica_roles
                    if state.target_prefill != prev.target_prefill:
                        # Pool split changed. A replica's role is fixed at
                        # engine start (nothing migrates a live engine), so
                        # replicas whose role no longer fits the new split
                        # are stale — drain exactly THOSE (a correctly-roled
                        # starting replica must survive); reconcile starts
                        # correctly-roled replacements via _pick_role.
                        live = list(state.replica_tags) + [
                            t for _, t, _ in state.starting
                        ]
                        roles = state.replica_roles
                        if state.target_prefill <= 0:
                            stale = [t for t in live if roles.get(t)]
                        else:
                            stale = [t for t in live if not roles.get(t)]
                            pre = [t for t in live
                                   if roles.get(t) == "prefill"]
                            dec = [t for t in live
                                   if roles.get(t) == "decode"]
                            # Pool excess: keep the oldest up to target.
                            stale += pre[state.target_prefill:]
                            stale += dec[
                                state.target_replicas - state.target_prefill:
                            ]
                        if stale:
                            self._drain(state, len(stale), tags=set(stale))
                    new_cfg = spec["opts"].get("user_config")
                    if new_cfg is not None and new_cfg != prev.spec["opts"].get("user_config"):
                        reconfigure_refs += [
                            r.reconfigure.remote(new_cfg) for r in state.replicas
                        ]
                elif prev is not None:
                    # Code changed: old replicas are stale — drain them all.
                    self._drain(prev, len(prev.replicas) + len(prev.starting))
                deployments[name] = state
            # Kill replicas of deployments that disappeared.
            for name, prev in old["deployments"].items():
                if name not in deployments:
                    self._drain(prev, len(prev.replicas) + len(prev.starting))
            self._apps[app_name] = {
                "deployments": deployments,
                "route_prefix": route_prefix,
                "ingress": ingress_name,
                "streaming": ingress_streaming,
            }
            self._version += 1
        for ref in reconfigure_refs:
            try:
                ray_tpu.get(ref, timeout=30.0)
            except Exception:  # noqa: BLE001
                pass
        self._reconcile()

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            app = self._apps.pop(app_name, None)
            if app:
                for state in app["deployments"].values():
                    self._drain(state, len(state.replicas) + len(state.starting))
                self._version += 1

    def shutdown(self) -> None:
        with self._lock:
            for app_name in list(self._apps):
                self.delete_application(app_name)
            self._shutdown = True

    # ------------------------------------------------------------- routing
    def get_deployment_info(self, app_name: str, deployment_name: str) -> Optional[Dict]:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            state = app["deployments"].get(deployment_name)
            if state is None:
                return None
            return {
                "version": self._version,
                "replicas": list(state.replicas),
                "replica_tags": list(state.replica_tags),
                "batch_methods": state.spec.get("batch_methods", {}),
                "max_ongoing_requests": state.spec["opts"]["max_ongoing_requests"],
                "prefix_affinity": state.spec["opts"].get(
                    "prefix_affinity_routing", True
                ),
                # Aligned with `replicas`: each entry is the replica's last
                # piggybacked engine telemetry (None when absent/stale) —
                # the fleet router's affinity + load inputs.
                "replica_meta": [
                    (state.replica_meta.get(t) or {}).get("engine")
                    for t in state.replica_tags
                ],
                # Disaggregated pools: the prefill-pool target (0 =
                # colocated) and each replica's controller-assigned role —
                # the router's pool split uses engine-telemetry roles, but
                # these let it know a deployment IS disaggregated before
                # first telemetry, and back tests/introspection.
                "prefill_replicas": state.target_prefill,
                "replica_roles": [
                    state.replica_roles.get(t) for t in state.replica_tags
                ],
                "status": state.status,
            }

    def routing_snapshot(self) -> Dict[str, Dict[str, str]]:
        """route_prefix -> {app, ingress} for HTTP proxies."""
        with self._lock:
            return {
                app["route_prefix"]: {
                    "app": name,
                    "ingress": app["ingress"],
                    "streaming": app.get("streaming", False),
                }
                for name, app in self._apps.items()
                if app["route_prefix"]
            }

    def app_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """app_name -> {ingress, streaming} — name-addressed ingress lookup
        (the gRPC proxy addresses apps by NAME; the HTTP route table is
        keyed by prefix and drops prefix-less apps)."""
        with self._lock:
            return {
                name: {
                    "ingress": app["ingress"],
                    "streaming": app.get("streaming", False),
                }
                for name, app in self._apps.items()
            }

    def version(self) -> int:
        return self._version

    def status(self) -> Dict[str, Any]:
        """Reference: `serve.status()` → application/deployment statuses."""
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                deps = {}
                all_running = True
                for dname, state in app["deployments"].items():
                    running = len(state.replicas)
                    deps[dname] = {
                        "status": state.status,
                        "replica_states": {"RUNNING": running},
                        "target_replicas": state.target_replicas,
                    }
                    if state.status != "HEALTHY":
                        all_running = False
                out[name] = {
                    "status": "RUNNING" if all_running else "DEPLOYING",
                    "deployments": deps,
                    "route_prefix": app["route_prefix"],
                }
            return out

    # ---------------------------------------------------------- autoscaling
    def record_request_metrics(
        self,
        app_name: str,
        deployment_name: str,
        ongoing: float,
        router_id: str = "",
    ):
        """Routers report their outstanding-request counts (reference:
        `autoscaling_metrics.py` pushes replica queue lengths). Reports are
        keyed by `router_id` and SUMMED across live routers — a router that
        stops reporting expires after `_ROUTER_REPORT_TTL_S`."""
        now = time.monotonic()
        with self._lock:
            app = self._apps.get(app_name)
            if not app:
                return
            state = app["deployments"].get(deployment_name)
            if not state:
                return
            state.router_reports[router_id] = [float(ongoing), now]
            # The EMA advances inside _maybe_autoscale (once per report —
            # updating it here too would double-decay it).
            self._maybe_autoscale(state)

    def _maybe_autoscale(self, state: _DeploymentState):
        """Scale decision from the fleet policy (`serve/fleet/autoscale`):
        router-outstanding pressure (summed across routers) OR engine
        queue-depth / TTFT-tail pressure scales up; scale-down additionally
        requires the coldest replica's prefix-hit economics to agree.
        Called on every router report AND every reconcile pass — an idle
        deployment whose routers went away still scales down."""
        cfg = state.spec["opts"].get("autoscaling_config")
        if not cfg:
            return
        from .fleet import FleetSignals, decide_scale

        now = time.monotonic()
        engines = [
            m["engine"]
            for m in state.replica_meta.values()
            if m and m.get("engine")
        ]
        # Refresh the EMA toward the current router total so pressure decays
        # once routers stop reporting (expired reporters drop out of the
        # sum) — but only while SOME signal source is live: with no live
        # router reports and no engine telemetry the controller is blind,
        # and a blind decay-to-zero would scale down under in-flight work
        # (a router only reports on new submissions).
        total = state.ongoing_total(now)
        if state.router_reports or engines:
            state.ongoing_ema = 0.8 * state.ongoing_ema + 0.2 * total

        def pool_signals(pool_engines, replicas, ongoing):
            p_ttfts = [
                e["ttft_p99_s"] for e in pool_engines
                if e.get("ttft_p99_s") is not None
            ]
            return FleetSignals(
                replicas=replicas,
                ongoing=ongoing,
                queue_depth=float(
                    sum(e.get("queue_depth") or 0 for e in pool_engines)
                ),
                running=float(
                    sum(e.get("running") or 0 for e in pool_engines)
                ),
                ttft_p99_s=max(p_ttfts) if p_ttfts else None,
                hit_rates=[e.get("prefix_hit_rate") for e in pool_engines],
            )

        if state.target_prefill > 0:
            # Disaggregated pools scale on their OWN signals: the TTFT
            # tail is made in the prefill pool, queue/in-flight pressure
            # lives in the decode pool (fleet/autoscale.py rationale).
            from .fleet import decide_scale_disagg

            pre = [e for e in engines if e.get("role") == "prefill"]
            dec = [e for e in engines if e.get("role") == "decode"]
            n_pre = sum(
                1 for t in state.replica_tags
                if state.replica_roles.get(t) == "prefill"
            )
            dp, dd = decide_scale_disagg(
                pool_signals(pre, n_pre, 0.0),
                pool_signals(
                    dec, len(state.replicas) - n_pre, state.ongoing_ema
                ),
                target_ongoing_requests=cfg["target_ongoing_requests"],
                target_queue_depth=cfg.get("target_queue_depth", 4.0),
                ttft_p99_target_s=cfg.get("ttft_p99_target_s"),
                downscale_hit_rate=cfg.get("downscale_hit_rate", 0.2),
            )
            # Both pools keep >= 1 replica and the TOTAL respects the
            # deployment's min/max band and scale delays.
            target_decode = state.target_replicas - state.target_prefill
            new_prefill = max(state.target_prefill + dp, 1)
            new_decode = max(target_decode + dd, 1)
            new_total = min(
                max(new_prefill + new_decode, cfg["min_replicas"]),
                cfg["max_replicas"],
            )
            overflow = (new_prefill + new_decode) - new_total
            if overflow > 0:
                # Band clamp gives GROWTH back first: a pool that did not
                # ask to grow is never cut below its current target just
                # because the other pool hit the ceiling.
                give = min(
                    overflow, max(new_prefill - state.target_prefill, 0)
                )
                new_prefill -= give
                new_decode = max(new_decode - (overflow - give), 1)
            elif overflow < 0:
                # min_replicas floor raise: decode absorbs it (extra decode
                # lanes are always usable; extra prefill replicas idle).
                new_decode = new_total - new_prefill
            if new_total == state.target_replicas:
                # No total change = nothing to actuate: roles are assigned
                # at replica START (_pick_role) and nothing migrates a live
                # replica between pools, so acting on a pure rebalance
                # (dp=+1/dd=-1) would drift target_prefill away from the
                # fleet's real composition forever.
                return
            delay = (
                cfg["upscale_delay_s"]
                if new_total > state.target_replicas
                else cfg["downscale_delay_s"]
            )
            if now - state.last_scale_action_t <= delay:
                return
            delta = new_total - state.target_replicas
            state.target_prefill = new_prefill
            state.target_replicas = new_total
        else:
            delta = decide_scale(
                pool_signals(engines, len(state.replicas), state.ongoing_ema),
                target_ongoing_requests=cfg["target_ongoing_requests"],
                target_queue_depth=cfg.get("target_queue_depth", 4.0),
                ttft_p99_target_s=cfg.get("ttft_p99_target_s"),
                downscale_hit_rate=cfg.get("downscale_hit_rate", 0.2),
            )
            if (
                delta > 0
                and state.target_replicas < cfg["max_replicas"]
                and now - state.last_scale_action_t > cfg["upscale_delay_s"]
            ):
                state.target_replicas += 1
            elif (
                delta < 0
                and state.target_replicas > cfg["min_replicas"]
                and now - state.last_scale_action_t > cfg["downscale_delay_s"]
            ):
                state.target_replicas -= 1
            else:
                return
        state.last_scale_action_t = now
        self._version += 1
        try:
            from ..util.metrics import serve_fleet_metrics

            name = state.spec["name"]
            m = serve_fleet_metrics()
            m["serve_autoscale_decisions_total"].inc(
                1.0,
                tags={"deployment": name,
                      "direction": "up" if delta > 0 else "down"},
            )
            m["serve_deployment_target_replicas"].set(
                float(state.target_replicas), tags={"deployment": name}
            )
        except Exception:  # noqa: BLE001 — metrics never load-bearing
            pass

    # ------------------------------------------------------------ reconcile
    def _reconcile_loop(self):
        while not self._shutdown:
            time.sleep(1.0)
            try:
                self._reconcile()
            except Exception:  # noqa: BLE001
                pass

    def _reconcile(self):
        """Health-check and converge replica counts. The state lock is held
        only for snapshot/apply; pings run in parallel outside it so a dead
        replica can't stall routing or deploy calls."""
        import ray_tpu

        with self._reconcile_lock:
            with self._lock:
                work = [
                    (app_name, dname, state, list(state.replicas), list(state.replica_tags))
                    for app_name, app in self._apps.items()
                    for dname, state in app["deployments"].items()
                ]
            for app_name, dname, state, replicas, tags in work:
                with self._lock:
                    starting = list(state.starting)
                probes = list(replicas) + [h for h, _, _ in starting]
                # Health probe + fleet telemetry in one RPC: an answered
                # telemetry() IS the liveness signal, and LLM replicas ship
                # their hot-prefix digest / queue depth / TTFT tail along
                # with it (routers read it back via get_deployment_info).
                refs = [h.telemetry.remote() for h in probes]
                ready = set()
                telem: Dict[Any, Any] = {}
                if refs:
                    done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5.0)
                    for ref in done:
                        try:
                            telem[ref] = ray_tpu.get(ref)
                            ready.add(ref)
                        except Exception:  # noqa: BLE001
                            pass
                ready_refs = refs[: len(replicas)]
                starting_refs = refs[len(replicas):]
                now = time.time()
                mono = time.monotonic()
                startup_tmo = float(
                    state.spec["opts"].get("replica_startup_timeout_s") or 600.0
                )

                keep, promote, kill = [], [], []
                meta_updates: Dict[str, Dict[str, Any]] = {}
                # READY replicas: a missed ping is counted, not fatal — a
                # replica busy with a long batch stays ROUTED until three
                # consecutive misses prove it wedged/dead (previously one
                # missed window silently LEAKED the actor and respawned).
                for h, t, r in zip(replicas, tags, ready_refs):
                    if r in ready:
                        state.miss_counts.pop(t, None)
                        keep.append((h, t))
                        v = telem.get(r)
                        if isinstance(v, dict) and v.get("engine") is not None:
                            meta_updates[t] = {"t": mono, "engine": v["engine"]}
                    else:
                        m = state.miss_counts.get(t, 0) + 1
                        state.miss_counts[t] = m
                        (kill if m >= 3 else keep).append((h, t))
                # STARTING replicas: a ping answer means __init__ finished →
                # promote to routable; silence is normal (model load/compile)
                # until the startup timeout.
                still_starting = []
                for (h, t, t0), r in zip(starting, starting_refs):
                    if r in ready:
                        promote.append((h, t))
                        # Telemetry lands WITH the promoting probe, so a
                        # just-promoted LLM replica is affinity-routable the
                        # moment serve.run's health wait returns.
                        v = telem.get(r)
                        if isinstance(v, dict) and v.get("engine") is not None:
                            meta_updates[t] = {"t": mono, "engine": v["engine"]}
                    elif now - t0 > startup_tmo:
                        kill.append((h, t))
                    else:
                        still_starting.append((h, t, t0))

                with self._lock:
                    # Staleness check BEFORE any kill/apply: an in-place
                    # redeploy SHARES the replica lists by reference, so a
                    # kill issued against a stale snapshot would leave a
                    # dead handle routable in the successor state.
                    app = self._apps.get(app_name)
                    if app is None or app["deployments"].get(dname) is not state:
                        continue  # redeployed/removed while we were pinging
                    routable = keep + promote
                    changed = (
                        [h for h, _ in routable] != state.replicas
                        or bool(kill)
                    )
                    state.replicas = [h for h, _ in routable]
                    state.replica_tags = [t for _, t in routable]
                    state.starting = still_starting
                    need = state.target_replicas - len(state.replicas) - len(
                        state.starting
                    )
                    excess = -need
                for h, t in kill:
                    state.miss_counts.pop(t, None)
                    state.replica_roles.pop(t, None)
                    try:
                        ray_tpu.kill(h)  # never leak a replaced replica
                    except Exception:  # noqa: BLE001
                        pass
                for _ in range(max(need, 0)):
                    self._start_replica(app_name, dname, state)
                    changed = True
                if excess > 0:
                    with self._lock:
                        self._drain(state, excess)
                    changed = True
                with self._lock:
                    # Telemetry bookkeeping: adopt this pass's readings and
                    # drop tags that are no longer routable (a drained
                    # replica's digest must not keep attracting traffic).
                    live_tags = set(state.replica_tags)
                    for t, m in meta_updates.items():
                        if t in live_tags:
                            state.replica_meta[t] = m
                    for t in list(state.replica_meta):
                        if t not in live_tags:
                            del state.replica_meta[t]
                    state.status = (
                        "HEALTHY"
                        if len(state.replicas) == state.target_replicas
                        else "UPDATING"
                    )
                    if changed:
                        self._version += 1
                    # Engine-metrics autoscale tick: pressure measured AT
                    # the engines must move targets even when no router is
                    # reporting (idle fleets still need scale-down).
                    try:
                        self._maybe_autoscale(state)
                    except Exception:  # noqa: BLE001
                        pass

    def _pick_role(self, state: _DeploymentState) -> Optional[str]:
        """Role for the next replica of a disaggregated deployment: fill
        the prefill pool to its target first, decode takes the rest. None
        for colocated deployments (no role injected). Called under the
        state lock."""
        if state.target_prefill <= 0:
            return None
        live = set(state.replica_tags) | {t for _, t, _ in state.starting}
        n_prefill = sum(
            1 for t in live if state.replica_roles.get(t) == "prefill"
        )
        return "prefill" if n_prefill < state.target_prefill else "decode"

    def _start_replica(self, app_name: str, dname: str, state: _DeploymentState):
        import ray_tpu
        from .replica import Replica

        spec = state.spec
        with self._lock:
            tag = f"{app_name}#{dname}#{state.next_replica_id}"
            state.next_replica_id += 1
            role = self._pick_role(state)
            if role is not None:
                state.replica_roles[tag] = role
        actor_opts = dict(spec["opts"].get("ray_actor_options") or {})
        RemoteReplica = ray_tpu.remote(Replica)
        if actor_opts:
            RemoteReplica = RemoteReplica.options(**actor_opts)
        handle = RemoteReplica.remote(
            app_name,
            dname,
            tag,
            spec["cls"],
            spec["init_args"],
            spec["opts"].get("user_config"),
            role,
        )
        with self._lock:
            app = self._apps.get(app_name)
            live = app is not None and app["deployments"].get(dname) is state
            if live:
                # New replicas are STARTING (unroutable) until their first
                # answered ping proves __init__ completed.
                state.starting.append((handle, tag, time.time()))
        if not live:
            # The deployment was replaced/deleted while the actor spawned —
            # appending to the orphaned state would leak a live replica.
            import ray_tpu

            state.replica_roles.pop(tag, None)
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass

    def _drain(self, state: _DeploymentState, n: int,
               tags: Optional[set] = None):
        """Kill up to `n` replicas. `tags` narrows the victims to exactly
        that set (pool-split redeploys drain the role-STALE replicas, not
        whatever drains first); None keeps the default order — unready
        (starting) replicas first, then _drain_pool_pick."""
        import ray_tpu

        for _ in range(n):
            handle = tag = None
            # Unready (starting) replicas go first: they serve nothing yet.
            if state.starting:
                if tags is None:
                    handle, tag, _t0 = state.starting.pop()
                else:
                    for j in range(len(state.starting) - 1, -1, -1):
                        if state.starting[j][1] in tags:
                            handle, tag, _t0 = state.starting.pop(j)
                            break
            if handle is None and state.replicas:
                if tags is not None:
                    i = next(
                        (i for i in range(len(state.replica_tags) - 1, -1, -1)
                         if state.replica_tags[i] in tags),
                        None,
                    )
                else:
                    i = _drain_pool_pick(state)
                if i is None:
                    if tags is not None:
                        break  # no tagged victim left
                    handle = state.replicas.pop()
                    tag = state.replica_tags.pop()
                else:
                    handle = state.replicas.pop(i)
                    tag = state.replica_tags.pop(i)
            if handle is None:
                break
            # Drop the drained replica's miss counter: leaving it would leak
            # an entry per replica generation (redeploy/scale-down/delete)
            # and poison a later replica that reuses the tag. Its telemetry
            # goes too — a dead replica's digest must not attract traffic.
            state.miss_counts.pop(tag, None)
            state.replica_meta.pop(tag, None)
            state.replica_roles.pop(tag, None)
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
            # A dead replica's metric series (engine gauges/histograms tagged
            # with its replica id) must leave /metrics now, not linger until
            # the controller's staleness sweep.
            try:
                from ..util.metrics import prune_series

                prune_series({"replica": tag})
            except Exception:  # noqa: BLE001
                pass

    def ping(self) -> str:
        return "ok"
