"""ServeController actor (reference: `serve/_private/controller.py:89`,
deployment reconciler `serve/_private/deployment_state.py:1210,2307`,
autoscaling `serve/_private/autoscaling_policy.py`).

Owns the desired state (applications → deployments → target replica counts),
reconciles it against live replica actors, and serves routing info to
routers/proxies. The reference's LongPollHost broadcast becomes versioned
snapshots that routers re-fetch when stale (short-poll at the router's
refresh interval — no blocking calls into the single-threaded controller).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"


class _DeploymentState:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.target_replicas: int = spec["opts"]["num_replicas"]
        self.replicas: List = []  # READY ActorHandles (routable)
        self.replica_tags: List[str] = []
        # Replicas whose __init__ has not answered a ping yet (model load +
        # jit compile can take MINUTES for LLM replicas — the reference's
        # DeploymentState keeps a STARTING state for exactly this;
        # `deployment_state.py:1210`). Not routable, not respawn-eligible
        # until replica_startup_timeout_s.
        self.starting: List = []  # [(handle, tag, started_at)]
        self.next_replica_id = 0
        # Consecutive missed pings per READY replica tag; replaced at 3.
        self.miss_counts: Dict[str, int] = {}
        # autoscaling bookkeeping
        self.ongoing_ema: float = 0.0
        self.last_scale_action_t: float = 0.0
        self.status: str = "UPDATING"


class ServeController:
    def __init__(self):
        self._lock = threading.RLock()          # guards state reads/writes (brief)
        self._reconcile_lock = threading.Lock()  # serializes reconcile passes
        self._apps: Dict[str, Dict[str, Any]] = {}  # app -> {deployments, route_prefix, ingress}
        self._version = 0
        self._shutdown = False
        self._reconciler = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._reconciler.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(
        self,
        app_name: str,
        dep_specs: List[Dict[str, Any]],
        route_prefix: str,
        ingress_name: str,
        ingress_streaming: bool = False,
    ) -> None:
        import ray_tpu

        with self._lock:
            old = self._apps.get(app_name, {"deployments": {}})
            deployments = {}
            reconfigure_refs = []
            for spec in dep_specs:
                name = spec["name"]
                prev = old["deployments"].get(name)
                state = _DeploymentState(spec)
                if prev is not None and prev.spec["cls"] == spec["cls"]:
                    # In-place update: keep live replicas, adopt new targets,
                    # and push the (possibly changed) user_config to them.
                    state.replicas = prev.replicas
                    state.replica_tags = prev.replica_tags
                    state.starting = prev.starting
                    state.miss_counts = prev.miss_counts
                    state.next_replica_id = prev.next_replica_id
                    new_cfg = spec["opts"].get("user_config")
                    if new_cfg is not None and new_cfg != prev.spec["opts"].get("user_config"):
                        reconfigure_refs += [
                            r.reconfigure.remote(new_cfg) for r in state.replicas
                        ]
                elif prev is not None:
                    # Code changed: old replicas are stale — drain them all.
                    self._drain(prev, len(prev.replicas) + len(prev.starting))
                deployments[name] = state
            # Kill replicas of deployments that disappeared.
            for name, prev in old["deployments"].items():
                if name not in deployments:
                    self._drain(prev, len(prev.replicas) + len(prev.starting))
            self._apps[app_name] = {
                "deployments": deployments,
                "route_prefix": route_prefix,
                "ingress": ingress_name,
                "streaming": ingress_streaming,
            }
            self._version += 1
        for ref in reconfigure_refs:
            try:
                ray_tpu.get(ref, timeout=30.0)
            except Exception:  # noqa: BLE001
                pass
        self._reconcile()

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            app = self._apps.pop(app_name, None)
            if app:
                for state in app["deployments"].values():
                    self._drain(state, len(state.replicas) + len(state.starting))
                self._version += 1

    def shutdown(self) -> None:
        with self._lock:
            for app_name in list(self._apps):
                self.delete_application(app_name)
            self._shutdown = True

    # ------------------------------------------------------------- routing
    def get_deployment_info(self, app_name: str, deployment_name: str) -> Optional[Dict]:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            state = app["deployments"].get(deployment_name)
            if state is None:
                return None
            return {
                "version": self._version,
                "replicas": list(state.replicas),
                "replica_tags": list(state.replica_tags),
                "batch_methods": state.spec.get("batch_methods", {}),
                "max_ongoing_requests": state.spec["opts"]["max_ongoing_requests"],
                "status": state.status,
            }

    def routing_snapshot(self) -> Dict[str, Dict[str, str]]:
        """route_prefix -> {app, ingress} for HTTP proxies."""
        with self._lock:
            return {
                app["route_prefix"]: {
                    "app": name,
                    "ingress": app["ingress"],
                    "streaming": app.get("streaming", False),
                }
                for name, app in self._apps.items()
                if app["route_prefix"]
            }

    def app_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """app_name -> {ingress, streaming} — name-addressed ingress lookup
        (the gRPC proxy addresses apps by NAME; the HTTP route table is
        keyed by prefix and drops prefix-less apps)."""
        with self._lock:
            return {
                name: {
                    "ingress": app["ingress"],
                    "streaming": app.get("streaming", False),
                }
                for name, app in self._apps.items()
            }

    def version(self) -> int:
        return self._version

    def status(self) -> Dict[str, Any]:
        """Reference: `serve.status()` → application/deployment statuses."""
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                deps = {}
                all_running = True
                for dname, state in app["deployments"].items():
                    running = len(state.replicas)
                    deps[dname] = {
                        "status": state.status,
                        "replica_states": {"RUNNING": running},
                        "target_replicas": state.target_replicas,
                    }
                    if state.status != "HEALTHY":
                        all_running = False
                out[name] = {
                    "status": "RUNNING" if all_running else "DEPLOYING",
                    "deployments": deps,
                    "route_prefix": app["route_prefix"],
                }
            return out

    # ---------------------------------------------------------- autoscaling
    def record_request_metrics(self, app_name: str, deployment_name: str, ongoing: float):
        """Routers report their outstanding-request counts (reference:
        `autoscaling_metrics.py` pushes replica queue lengths)."""
        with self._lock:
            app = self._apps.get(app_name)
            if not app:
                return
            state = app["deployments"].get(deployment_name)
            if not state:
                return
            state.ongoing_ema = 0.8 * state.ongoing_ema + 0.2 * ongoing
            self._maybe_autoscale(state)

    def _maybe_autoscale(self, state: _DeploymentState):
        cfg = state.spec["opts"].get("autoscaling_config")
        if not cfg:
            return
        now = time.monotonic()
        per_replica = state.ongoing_ema / max(len(state.replicas), 1)
        if (
            per_replica > cfg["target_ongoing_requests"]
            and state.target_replicas < cfg["max_replicas"]
            and now - state.last_scale_action_t > cfg["upscale_delay_s"]
        ):
            state.target_replicas += 1
            state.last_scale_action_t = now
            self._version += 1
        elif (
            per_replica < 0.5 * cfg["target_ongoing_requests"]
            and state.target_replicas > cfg["min_replicas"]
            and now - state.last_scale_action_t > cfg["downscale_delay_s"]
        ):
            state.target_replicas -= 1
            state.last_scale_action_t = now
            self._version += 1

    # ------------------------------------------------------------ reconcile
    def _reconcile_loop(self):
        while not self._shutdown:
            time.sleep(1.0)
            try:
                self._reconcile()
            except Exception:  # noqa: BLE001
                pass

    def _reconcile(self):
        """Health-check and converge replica counts. The state lock is held
        only for snapshot/apply; pings run in parallel outside it so a dead
        replica can't stall routing or deploy calls."""
        import ray_tpu

        with self._reconcile_lock:
            with self._lock:
                work = [
                    (app_name, dname, state, list(state.replicas), list(state.replica_tags))
                    for app_name, app in self._apps.items()
                    for dname, state in app["deployments"].items()
                ]
            for app_name, dname, state, replicas, tags in work:
                with self._lock:
                    starting = list(state.starting)
                probes = list(replicas) + [h for h, _, _ in starting]
                refs = [h.ping.remote() for h in probes]
                ready = set()
                if refs:
                    done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5.0)
                    for ref in done:
                        try:
                            ray_tpu.get(ref)
                            ready.add(ref)
                        except Exception:  # noqa: BLE001
                            pass
                ready_refs = refs[: len(replicas)]
                starting_refs = refs[len(replicas):]
                now = time.time()
                startup_tmo = float(
                    state.spec["opts"].get("replica_startup_timeout_s") or 600.0
                )

                keep, promote, kill = [], [], []
                # READY replicas: a missed ping is counted, not fatal — a
                # replica busy with a long batch stays ROUTED until three
                # consecutive misses prove it wedged/dead (previously one
                # missed window silently LEAKED the actor and respawned).
                for h, t, r in zip(replicas, tags, ready_refs):
                    if r in ready:
                        state.miss_counts.pop(t, None)
                        keep.append((h, t))
                    else:
                        m = state.miss_counts.get(t, 0) + 1
                        state.miss_counts[t] = m
                        (kill if m >= 3 else keep).append((h, t))
                # STARTING replicas: a ping answer means __init__ finished →
                # promote to routable; silence is normal (model load/compile)
                # until the startup timeout.
                still_starting = []
                for (h, t, t0), r in zip(starting, starting_refs):
                    if r in ready:
                        promote.append((h, t))
                    elif now - t0 > startup_tmo:
                        kill.append((h, t))
                    else:
                        still_starting.append((h, t, t0))

                with self._lock:
                    # Staleness check BEFORE any kill/apply: an in-place
                    # redeploy SHARES the replica lists by reference, so a
                    # kill issued against a stale snapshot would leave a
                    # dead handle routable in the successor state.
                    app = self._apps.get(app_name)
                    if app is None or app["deployments"].get(dname) is not state:
                        continue  # redeployed/removed while we were pinging
                    routable = keep + promote
                    changed = (
                        [h for h, _ in routable] != state.replicas
                        or bool(kill)
                    )
                    state.replicas = [h for h, _ in routable]
                    state.replica_tags = [t for _, t in routable]
                    state.starting = still_starting
                    need = state.target_replicas - len(state.replicas) - len(
                        state.starting
                    )
                    excess = -need
                for h, t in kill:
                    state.miss_counts.pop(t, None)
                    try:
                        ray_tpu.kill(h)  # never leak a replaced replica
                    except Exception:  # noqa: BLE001
                        pass
                for _ in range(max(need, 0)):
                    self._start_replica(app_name, dname, state)
                    changed = True
                if excess > 0:
                    with self._lock:
                        self._drain(state, excess)
                    changed = True
                with self._lock:
                    state.status = (
                        "HEALTHY"
                        if len(state.replicas) == state.target_replicas
                        else "UPDATING"
                    )
                    if changed:
                        self._version += 1

    def _start_replica(self, app_name: str, dname: str, state: _DeploymentState):
        import ray_tpu
        from .replica import Replica

        spec = state.spec
        tag = f"{app_name}#{dname}#{state.next_replica_id}"
        state.next_replica_id += 1
        actor_opts = dict(spec["opts"].get("ray_actor_options") or {})
        RemoteReplica = ray_tpu.remote(Replica)
        if actor_opts:
            RemoteReplica = RemoteReplica.options(**actor_opts)
        handle = RemoteReplica.remote(
            app_name,
            dname,
            tag,
            spec["cls"],
            spec["init_args"],
            spec["opts"].get("user_config"),
        )
        with self._lock:
            app = self._apps.get(app_name)
            live = app is not None and app["deployments"].get(dname) is state
            if live:
                # New replicas are STARTING (unroutable) until their first
                # answered ping proves __init__ completed.
                state.starting.append((handle, tag, time.time()))
        if not live:
            # The deployment was replaced/deleted while the actor spawned —
            # appending to the orphaned state would leak a live replica.
            import ray_tpu

            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass

    def _drain(self, state: _DeploymentState, n: int):
        import ray_tpu

        for _ in range(n):
            # Unready (starting) replicas go first: they serve nothing yet.
            if state.starting:
                handle, tag, _t0 = state.starting.pop()
            elif state.replicas:
                handle = state.replicas.pop()
                tag = state.replica_tags.pop()
            else:
                break
            # Drop the drained replica's miss counter: leaving it would leak
            # an entry per replica generation (redeploy/scale-down/delete)
            # and poison a later replica that reuses the tag.
            state.miss_counts.pop(tag, None)
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
            # A dead replica's metric series (engine gauges/histograms tagged
            # with its replica id) must leave /metrics now, not linger until
            # the controller's staleness sweep.
            try:
                from ..util.metrics import prune_series

                prune_series({"replica": tag})
            except Exception:  # noqa: BLE001
                pass

    def ping(self) -> str:
        return "ok"
