"""Fleet serving plane — the L3/L5 layer over the single-replica engine
(reference analog: Ray Serve's router/autoscaler split, PAPER.md §1).

Three cooperating planes turn a set of `LLMDeployment` replicas into a
*fleet*:

  * `routing` — prefix-affinity request placement: the router derives a
    routing key chain from the prompt's leading full KV blocks (the SAME
    chained blake2b content hash `engine/kv_manager.py` registers blocks
    under), and steers the request to the replica whose advertised hot-
    prefix digest matches deepest.  Cold prefixes converge via rendezvous
    hashing; load skew falls back to power-of-two choices, and a replica
    past its spill threshold is never picked on affinity alone.
  * `autoscale` — engine-metrics scaling decisions: scale-up triggers on
    queue-depth / TTFT-tail pressure measured AT the engines, scale-down
    only when prefix-hit economics say a replica's cache is cold.
  * speculative decoding lives in the engine (`engine/spec.py` proposer +
    `models/gpt.py:verify_step_paged`) — the fleet bench measures its
    acceptance rate per replica.

Everything here is pure policy over plain data (no JAX, no actor calls):
the Router (`serve/handle.py`) and ServeController (`serve/controller.py`)
own the mechanics.
"""

from .autoscale import FleetSignals, decide_scale, decide_scale_disagg
from .routing import (
    DIGEST_HASH_BYTES,
    pick_replica,
    rendezvous_rank,
    routing_chain,
    split_pools,
)

__all__ = [
    "DIGEST_HASH_BYTES",
    "FleetSignals",
    "decide_scale",
    "decide_scale_disagg",
    "pick_replica",
    "rendezvous_rank",
    "routing_chain",
    "split_pools",
]
