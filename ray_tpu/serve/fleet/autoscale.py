"""Engine-metrics autoscaling policy (pure decision function).

The r5-era autoscaler consumed ONE signal: an EMA of router-reported
outstanding requests. That misses the two pressures that actually hurt an
LLM fleet — prompts queued INSIDE an engine waiting for KV admission, and
the TTFT tail those queues produce — and it happily killed replicas whose
prefix caches were serving most of the fleet's hits. This policy consumes
the engine metrics the replicas already export:

  * scale UP on queue pressure (`queue_depth` per replica over target) or
    TTFT-tail pressure (`ttft_p99_s` over target), whichever fires first —
    router-outstanding pressure (the legacy signal) still counts, summed
    correctly across routers;
  * scale DOWN only when the fleet is quiet AND the prefix-hit economics
    agree: the marginal replica's recent hit rate must be below
    `downscale_hit_rate` — a replica serving cache hits is cheaper to keep
    than to re-warm after the next burst.

The controller owns mechanics (delay gating via `last_scale_action_t`,
min/max clamping, applying the delta); this module owns only the verdict.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class FleetSignals:
    """One deployment's aggregated telemetry at decision time."""

    replicas: int                      # current routable replica count
    ongoing: float                     # outstanding reqs summed over routers
    queue_depth: float                 # engine admission queues, summed
    # Sequences currently DECODING across the fleet: a router can go silent
    # mid-generation (it only reports on new submissions), so in-flight
    # work must block scale-down on its own signal.
    running: float = 0.0
    ttft_p99_s: Optional[float] = None  # worst replica's TTFT tail
    # Per-replica recent prefix-hit rate (None = no telemetry / idle).
    hit_rates: List[Optional[float]] = dataclasses.field(default_factory=list)


def decide_scale(
    signals: FleetSignals,
    target_ongoing_requests: float,
    target_queue_depth: float,
    ttft_p99_target_s: Optional[float],
    downscale_hit_rate: float,
) -> int:
    """Return +1 (scale up), -1 (scale down), or 0 — pressure first, then
    economics. The caller applies its own delay/min/max gating."""
    n = max(signals.replicas, 1)
    ongoing_per = signals.ongoing / n
    queue_per = signals.queue_depth / n
    ttft_hot = (
        ttft_p99_target_s is not None
        and signals.ttft_p99_s is not None
        and signals.ttft_p99_s > ttft_p99_target_s
    )

    if (
        ongoing_per > target_ongoing_requests
        or queue_per > target_queue_depth
        or ttft_hot
    ):
        return 1

    quiet = (
        signals.queue_depth <= 0
        and signals.running <= 0
        and not ttft_hot
        and ongoing_per < 0.5 * target_ongoing_requests
    )
    if not quiet:
        return 0
    # Economics: only retire a replica whose cache is COLD. The coldest
    # replica is the drain candidate; an idle fleet with hot caches is a
    # warm pool, not waste. Missing telemetry reads as cold (0.0) — a
    # replica that reports nothing has nothing worth keeping warm.
    if signals.hit_rates:
        coldest = min(r if r is not None else 0.0 for r in signals.hit_rates)
        if coldest >= downscale_hit_rate:
            return 0
    return -1


def decide_scale_disagg(
    prefill: FleetSignals,
    decode: FleetSignals,
    target_ongoing_requests: float,
    target_queue_depth: float,
    ttft_p99_target_s: Optional[float],
    downscale_hit_rate: float,
) -> "tuple[int, int]":
    """Per-pool verdicts for a disaggregated deployment: (prefill_delta,
    decode_delta), each in {-1, 0, +1}.

    The pools scale on the signals they actually own (DistServe's core
    observation — prefill and decode saturate on different resources):

      * PREFILL pool — TTFT is made here (the pool computes prompts and
        emits first tokens), so the TTFT tail and the pool's admission
        queues drive it. Router-outstanding pressure is excluded: requests
        spend almost their whole life decoding, so the outstanding count
        says nothing about prefill capacity.
      * DECODE pool — queue depth, in-flight decode lanes, and the
        router-outstanding total (its proxy for inter-token pressure)
        drive it; the TTFT tail is excluded — a slow first token is never
        this pool's fault.

    Scale-down economics are unchanged per pool: quiet AND the pool's
    coldest cache below `downscale_hit_rate` (a prefill pool's warm system
    prompts are exactly the fleet-wide cache worth keeping)."""
    dp = decide_scale(
        dataclasses.replace(prefill, ongoing=0.0),
        target_ongoing_requests=target_ongoing_requests,
        target_queue_depth=target_queue_depth,
        ttft_p99_target_s=ttft_p99_target_s,
        downscale_hit_rate=downscale_hit_rate,
    )
    dd = decide_scale(
        dataclasses.replace(decode, ttft_p99_s=None),
        target_ongoing_requests=target_ongoing_requests,
        target_queue_depth=target_queue_depth,
        ttft_p99_target_s=None,
        downscale_hit_rate=downscale_hit_rate,
    )
    return dp, dd
