"""Prefix-affinity replica selection (pure policy, no actors, no JAX).

The routing key is the SAME chained blake2b content hash the engine's
`KVBlockManager` registers full KV blocks under: `routing_chain(prompt)`
hashes the prompt's leading full blocks into a chain h1..hB (hB commits to
every token in blocks 0..B-1).  A replica whose prefix cache holds the
first j blocks of that prompt has h1..hj in its hot-prefix digest, so the
deepest digest match predicts exactly how many blocks of prefill the
replica would skip.

Selection order (`pick_replica`):

  1. SPILL GUARD — replicas whose load (engine queue depth + the caller's
     own outstanding count) is at or past `spill_threshold` are excluded;
     affinity must never pile more requests onto an already-drowning
     replica.  If EVERY replica is past the threshold, fall through to
     pure power-of-two load balancing (placement quality is moot when the
     whole fleet is saturated).
  2. AFFINITY — among eligible replicas, pick the deepest digest match;
     ties break by lower load, then rendezvous rank (deterministic).
  3. RENDEZVOUS — cold prefix (no digest hit anywhere, or every digest is
     stale/absent): rendezvous-hash the deepest chain key over replica
     tags.  Identical prompts from ANY router converge on the same
     replica, so the second arrival hits the cache the first one warmed.
  4. POWER-OF-TWO — no routing key at all (prompt shorter than one block,
     non-LLM method): classic two-choices on load.

Digest entries travel the control plane truncated to `DIGEST_HASH_BYTES`
hex (the digest is advisory — a truncation collision merely routes to a
replica that turns out to miss; correctness-critical matching stays inside
the engine on full 16-byte hashes).
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List, Optional, Sequence, Tuple

# The hash AND its wire truncation are the kv_manager's: the router's
# chain entries must compare equal to replica digest entries byte for byte.
from ..engine.kv_manager import DIGEST_HASH_BYTES, _chain_hash

# Leading full blocks hashed into the routing key. Deeper adds nothing:
# affinity only needs to discriminate prefixes, not verify them.
MAX_ROUTING_BLOCKS = 8


def routing_chain(
    prompt: Sequence[int],
    block_size: int,
    max_blocks: int = MAX_ROUTING_BLOCKS,
) -> List[str]:
    """Chained content hashes (truncated hex) of the prompt's leading FULL
    blocks — `chain[i]` commits to blocks 0..i. Mirrors the engine's
    admission rule: the last prompt token never counts toward a cacheable
    block, so a prompt of exactly one block yields an empty chain."""
    if block_size <= 0 or len(prompt) <= 1:
        return []
    full = min((len(prompt) - 1) // block_size, max_blocks)
    chain: List[str] = []
    prev = b""
    for i in range(full):
        h = _chain_hash(prev, prompt[i * block_size:(i + 1) * block_size])
        chain.append(h[:DIGEST_HASH_BYTES].hex())
        prev = h
    return chain


def rendezvous_rank(key: str, tag: str) -> bytes:
    """Highest-random-weight score of (routing key, replica tag) — every
    router ranks replicas identically, so cold prefixes converge without
    any shared state. Also THE rendezvous hash for multiplexed-model
    routing (`handle.py._pick_replica` calls this) — one construction,
    tuned once."""
    return hashlib.blake2b(f"{key}:{tag}".encode(), digest_size=8).digest()


def _digest_depth(chain: Sequence[str], digest) -> int:
    """Deepest chain entry present in a replica's hot-prefix digest
    (1-based; 0 = no match). The digest is bounded and hot-ordered, so a
    shallow hash may have aged out while a deeper one survives — the
    deepest match alone is the signal."""
    if not digest:
        return 0
    d = digest if isinstance(digest, (set, frozenset)) else set(digest)
    for i in range(len(chain) - 1, -1, -1):
        if chain[i] in d:
            return i + 1
    return 0


def pick_replica(
    chain: Sequence[str],
    tags: Sequence[str],
    metas: Sequence[Optional[Dict]],
    outstanding: Dict[int, int],
    spill_threshold: int,
    rng: Optional[random.Random] = None,
) -> Tuple[int, str]:
    """Choose a replica index for one request.

    `metas[i]` is replica i's latest telemetry (None when stale/absent):
    `{"digest": [hex...], "queue_depth": int, ...}`. `outstanding` is the
    caller's local in-flight count per index — the freshest load signal it
    has between telemetry refreshes. Returns (index, reason) with reason in
    {"affinity", "rendezvous", "pow2", "spill"} for metrics/tests.
    """
    n = len(tags)
    if n == 0:
        raise ValueError("no replicas")
    if n == 1:
        return 0, "pow2"
    pick = rng or random

    def load(i: int) -> int:
        q = 0
        m = metas[i] if i < len(metas) else None
        if m:
            q = int(m.get("queue_depth") or 0)
        return q + int(outstanding.get(i, 0))

    eligible = [i for i in range(n) if load(i) < spill_threshold]
    if not eligible:
        # Whole fleet saturated: spread by load, ignore affinity.
        a, b = pick.sample(range(n), 2)
        return (a if load(a) <= load(b) else b), "spill"

    if chain:
        key = chain[-1]
        best, best_rank = None, None
        for i in eligible:
            depth = _digest_depth(chain, (metas[i] or {}).get("digest"))
            rank = (depth, -load(i), rendezvous_rank(key, tags[i]))
            if best_rank is None or rank > best_rank:
                best, best_rank = i, rank
        if best_rank[0] > 0:
            return best, "affinity"
        # Cold prefix everywhere (or digests stale): deterministic
        # convergence — the SECOND arrival of this prefix must find the
        # replica the first one warmed.
        best = max(eligible, key=lambda i: rendezvous_rank(key, tags[i]))
        return best, "rendezvous"

    # No routing key: power-of-two choices on load.
    if len(eligible) == 1:
        return eligible[0], "pow2"
    a, b = pick.sample(eligible, 2)
    return (a if load(a) <= load(b) else b), "pow2"


# --------------------------------------------------- disaggregated pools
def split_pools(
    roles: Sequence[Optional[str]],
) -> Tuple[List[int], List[int]]:
    """(prefill indices, decode indices) from per-replica pool roles.
    A replica with no role / role "mixed" belongs to neither pool —
    disaggregated orchestration only engages when BOTH pools are non-empty
    (`serve/handle.py`), so a mixed fleet keeps the colocated path. The
    pool split is what implements role routing: the router runs
    `pick_replica` over the PREFILL pool with the prompt's digest chain
    (deepest-affinity placement — that pool owns the prefix caches) and
    over the DECODE pool with no chain (pure load: its cache is fed by
    imports, so placement is about lane pressure, not affinity)."""
    prefill: List[int] = []
    decode: List[int] = []
    for i, role in enumerate(roles):
        if role == "prefill":
            prefill.append(i)
        elif role == "decode":
            decode.append(i)
    return prefill, decode
