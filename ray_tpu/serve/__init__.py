"""ray_tpu.serve — online model serving (reference: `python/ray/serve/`).

Control plane: ServeController actor reconciling deployment → replica-actor
state, with engine-metrics autoscaling (`fleet/autoscale.py`). Data plane:
client-side Router (prefix-affinity placement for LLM prompts via
`fleet/routing.py`, power-of-two-choices otherwise) → replica actors;
batch formation in the router so TPU replicas run one XLA program per
formed batch. See SURVEY.md §2.5 / §3.4 and README.md "Fleet serving".
"""

from .api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    grpc_port,
    http_port,
    run,
    run_config,
    shutdown,
    start,
    status,
)
from .batching import batch, multiplexed
from .context import get_multiplexed_model_id, get_replica_context
from .deployment import Application, AutoscalingConfig, Deployment, deployment
from .handle import DeploymentHandle, DeploymentResponse, DeploymentResponseGenerator
from .http_proxy import Request

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "AutoscalingConfig",
    "run",
    "run_config",
    "start",
    "delete",
    "status",
    "shutdown",
    "http_port",
    "batch",
    "multiplexed",
    "get_multiplexed_model_id",
    "get_replica_context",
    "DeploymentHandle",
    "DeploymentResponse",
    "grpc_port",
    "DeploymentResponseGenerator",
    "Request",
]


# Continuous-batching LLM engine (serve.engine) — lazy: LLMDeployment pulls
# in JAX + the model stack, which plain control-plane users never need.
_ENGINE_EXPORTS = frozenset(
    {"LLMDeployment", "InferenceEngine", "EngineOptions", "KVBlockManager"}
)
__all__ += ["LLMDeployment", "InferenceEngine", "EngineOptions", "KVBlockManager"]


def __getattr__(name):
    if name in _ENGINE_EXPORTS:
        from . import engine as _engine

        return getattr(_engine, name)
    raise AttributeError(name)


def ingress(*_a, **_k):
    """FastAPI-style ingress decorator is a no-op shim (no fastapi in the
    image); plain `__call__(request)` deployments cover HTTP ingress."""

    def wrap(cls):
        return cls

    return wrap
