"""gRPC ingress for Serve.

Reference analog: Serve's gRPCProxy (`serve/_private/proxy.py:556`) over
`serve.proto`. Contract: `ray_tpu.serve.RayTpuServe/Predict` (unary) and
`/PredictStream` (server streaming) carrying `ServeRequest`/`ServeReply`
(`ray_tpu/protocol/serve.proto`). Service wiring is a
`grpc.GenericRpcHandler` — no generated service stubs needed.

The deployment receives a `GRPCRequest` (payload bytes + method +
model id); whatever it returns is packed back into `ServeReply.payload`
(bytes passthrough, str utf-8, else JSON).
"""

from __future__ import annotations

import json
import time
from concurrent import futures
from typing import Optional

from ._common import response_bytes as _as_bytes

SERVICE = "ray_tpu.serve.RayTpuServe"


class GRPCRequest:
    """What a deployment's method receives for gRPC traffic."""

    def __init__(self, payload: bytes, method: str, multiplexed_model_id: str):
        self.payload = payload
        self.method = method
        self.multiplexed_model_id = multiplexed_model_id

    def json(self):
        return json.loads(self.payload or b"null")

    def text(self) -> str:
        return (self.payload or b"").decode()


class GRPCProxy:
    """NOTE: instantiated as a ray_tpu actor by `serve.start(grpc_options=...)`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        from ..protocol import serve_pb2

        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if method == f"/{SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._predict,
                        request_deserializer=serve_pb2.ServeRequest.FromString,
                        response_serializer=serve_pb2.ServeReply.SerializeToString,
                    )
                if method == f"/{SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._predict_stream,
                        request_deserializer=serve_pb2.ServeRequest.FromString,
                        response_serializer=serve_pb2.ServeReply.SerializeToString,
                    )
                return None

        self._pb = serve_pb2
        self._grpc = grpc
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def get_port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "ok"

    # ------------------------------------------------------------ handlers
    def _apps(self):
        """Name-addressed app map with a 1s TTL cache (same pattern as the
        HTTP proxy's route cache — two controller RPCs per request would
        make the controller the ingress bottleneck)."""
        import ray_tpu
        from .controller import CONTROLLER_NAME, SERVE_NAMESPACE

        now = time.monotonic()
        cached = getattr(self, "_apps_cache", None)
        if cached is not None and now - self._apps_cached_at < 1.0:
            return cached
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        self._apps_cache = ray_tpu.get(controller.app_snapshot.remote())
        self._apps_cached_at = now
        return self._apps_cache

    def _resolve(self, request, context):
        from .handle import DeploymentHandle

        apps = self._apps()
        app = request.app or "default"
        match = apps.get(app)
        if match is None:
            # One forced refresh: the app may have deployed inside the TTL.
            self._apps_cached_at = 0.0
            match = self._apps().get(app)
        if match is None:
            context.abort(
                self._grpc.StatusCode.NOT_FOUND,
                f"no Serve application {app!r}",
            )
        handle = DeploymentHandle(app, match["ingress"])
        req = GRPCRequest(
            request.payload, request.method, request.multiplexed_model_id
        )
        if request.multiplexed_model_id:
            handle = handle.options(
                multiplexed_model_id=request.multiplexed_model_id
            )
        return handle, req, match

    def _predict(self, request, context):
        handle, req, _ = self._resolve(request, context)
        if request.method:
            result = getattr(handle, request.method).remote(req).result(timeout_s=60.0)
        else:
            result = handle.remote(req).result(timeout_s=60.0)
        return self._pb.ServeReply(payload=_as_bytes(result))

    def _predict_stream(self, request, context):
        handle, req, _ = self._resolve(request, context)
        stream_handle = handle.options(stream=True)
        gen = (
            getattr(stream_handle, request.method).remote(req)
            if request.method
            else stream_handle.remote(req)
        )
        for chunk in gen:
            yield self._pb.ServeReply(payload=_as_bytes(chunk))

    def shutdown(self):
        self._server.stop(grace=0.5)
