"""Shared ingress helpers (HTTP + gRPC proxies)."""

from __future__ import annotations

import json


def response_bytes(value) -> bytes:
    """Response packing rule shared by every ingress: bytes passthrough,
    str utf-8, anything else JSON."""
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    return json.dumps(value).encode()
