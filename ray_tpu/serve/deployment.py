"""Deployment + Application graph (reference: `python/ray/serve/deployment.py`,
`api.py:449 serve.run`, deployment graphs via `deployment_graph_build.py`).

`@serve.deployment class D` → Deployment; `D.bind(args)` → Application node.
Binding another Application as an init arg builds a multi-deployment graph:
the child is deployed separately and the parent receives a DeploymentHandle
in its place (the reference's deployment-graph build pass).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """Reference: `serve/_private/autoscaling_policy.py` knobs, extended
    with the engine-metrics signals (`serve/fleet/autoscale.py`): scale-up
    also fires on per-replica engine queue depth or the TTFT tail, and
    scale-down additionally requires the coldest replica's recent
    prefix-hit rate to be below `downscale_hit_rate` (a hot cache is
    cheaper to keep than to re-warm)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # Engine-metrics signals (ignored for deployments without an engine).
    target_queue_depth: float = 4.0
    ttft_p99_target_s: Optional[float] = None
    downscale_hit_rate: float = 0.2


@dataclasses.dataclass
class DeploymentOptions:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    user_config: Optional[dict] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 10.0
    # How long a replica may sit in __init__ (model load + jit compile)
    # before the controller gives up and replaces it. LLM replicas
    # legitimately take minutes.
    replica_startup_timeout_s: float = 600.0
    max_num_models_per_replica: int = 3  # multiplexing LRU size
    # Fleet routing: steer requests to the replica whose hot-prefix digest
    # matches the prompt's leading KV blocks (serve/fleet/routing.py).
    # False = plain power-of-two (the bench baseline).
    prefix_affinity_routing: bool = True
    # Disaggregated prefill/decode serving (serve/README.md): > 0 splits
    # the replica set into a prefill pool of this size (engines started
    # with role="prefill") and a decode pool (role="decode", the rest).
    # The router then orchestrates prefill->handoff->decode per request,
    # shipping computed KV between pools over the bulk plane, and the
    # controller autoscales the two pools on their own signals (TTFT tail
    # -> prefill, queue/in-flight -> decode). 0 = colocated (default).
    prefill_replicas: int = 0


class Deployment:
    def __init__(self, cls_or_fn: Callable, name: str, options: DeploymentOptions):
        self._callable = cls_or_fn
        self._is_function = not isinstance(cls_or_fn, type)
        self.name = name
        self.opts = options

    def options(self, **kwargs) -> "Deployment":
        new_opts = dataclasses.replace(self.opts)
        for k, v in kwargs.items():
            if k == "autoscaling_config" and isinstance(v, dict):
                v = AutoscalingConfig(**v)
            if not hasattr(new_opts, k):
                raise ValueError(f"Unknown deployment option {k!r}")
            setattr(new_opts, k, v)
        return Deployment(self._callable, self.name, new_opts)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name})"


class Application:
    """A bound deployment node; may reference other Applications in args."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    def _flatten(self) -> List["Application"]:
        """Topological list of all apps in this graph, dependencies first."""
        seen: List[Application] = []

        def visit(app: Application):
            for a in list(app.init_args) + list(app.init_kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            if app not in seen:
                seen.append(app)

        visit(self)
        return seen


def deployment(
    _cls: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[int] = None,
    max_ongoing_requests: Optional[int] = None,
    user_config: Optional[dict] = None,
    autoscaling_config: Optional[dict] = None,
    ray_actor_options: Optional[dict] = None,
    replica_startup_timeout_s: Optional[float] = None,
):
    """`@serve.deployment` decorator (reference: `serve/api.py` `deployment`)."""

    def wrap(cls):
        opts = DeploymentOptions()
        if num_replicas is not None:
            opts.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            opts.max_ongoing_requests = max_ongoing_requests
        if user_config is not None:
            opts.user_config = user_config
        if autoscaling_config is not None:
            opts.autoscaling_config = (
                autoscaling_config
                if isinstance(autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config)
            )
        if ray_actor_options is not None:
            opts.ray_actor_options = dict(ray_actor_options)
        if replica_startup_timeout_s is not None:
            opts.replica_startup_timeout_s = float(replica_startup_timeout_s)
        return Deployment(cls, name or cls.__name__, opts)

    if _cls is not None:
        return wrap(_cls)
    return wrap
