"""`@serve.batch` and `@serve.multiplexed` (reference: `serve/batching.py`,
`serve/multiplex.py`).

TPU framing: batch formation happens in the *router* (requests accumulate up
to max_batch_size / batch_wait_timeout_s, then ship as ONE replica call) so a
replica executes one XLA program per formed batch — the reference batches
inside the replica's asyncio loop instead.
"""

from __future__ import annotations

import collections
import functools
from typing import Callable, Optional


class _BatchConfig:
    __slots__ = ("max_batch_size", "batch_wait_timeout_s")

    def __init__(self, max_batch_size: int, batch_wait_timeout_s: float):
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Mark a method as batch-handling: it receives a LIST of the single
    arguments callers passed to `.remote()` and must return a list of equal
    length."""

    def wrap(fn):
        fn._serve_batch_config = _BatchConfig(max_batch_size, batch_wait_timeout_s)
        return fn

    if _fn is not None:
        return wrap(_fn)
    return wrap


def multiplexed(
    _fn: Optional[Callable] = None,
    *,
    max_num_models_per_replica: int = 3,
):
    """Wrap a model-loader method with a per-replica LRU cache keyed by
    model_id (reference: `serve/multiplex.py` `_ModelMultiplexWrapper`)."""

    def wrap(fn):
        @functools.wraps(fn)
        def loader(self, model_id: str):
            cache = getattr(self, "_serve_multiplex_cache", None)
            if cache is None:
                cache = collections.OrderedDict()
                self._serve_multiplex_cache = cache
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = fn(self, model_id)
            cache[model_id] = model
            while len(cache) > max_num_models_per_replica:
                evicted_id, evicted = cache.popitem(last=False)
                del_fn = getattr(evicted, "__del__", None)
                if del_fn is not None:
                    try:
                        del_fn()
                    except Exception:  # noqa: BLE001
                        pass
            return model

        loader._serve_multiplexed = True
        return loader

    if _fn is not None:
        return wrap(_fn)
    return wrap
