"""DeploymentHandle + Router (reference: `serve/handle.py:827,894`,
`serve/_private/router.py:924` Router, `:295` PowerOfTwoChoicesReplicaScheduler).

The router lives client-side (in whichever process holds the handle):
prefix-affinity placement for LLM prompts (the fleet plane — see
`serve/fleet/routing.py`: the prompt's leading full KV blocks hash to a
routing key matched against each replica's piggybacked hot-prefix digest,
with rendezvous fallback for cold prefixes and power-of-two fallback under
load skew), plain power-of-two-choices over per-replica outstanding counts
otherwise, periodic snapshot refresh from the controller, and router-side
batch formation for `@serve.batch` methods (one replica call per formed
batch — one XLA program per batch on TPU replicas). Unary calls fail over
ONCE to a different replica when the picked one died between refreshes.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..util import flight, tracing

_ROUTER_REFRESH_S = 1.0

# Routing-key block size used before any replica telemetry reveals the
# engine's real one (matches EngineOptions.block_size's default).
_DEFAULT_ROUTING_BLOCK = 16
# Bound on the prefill leg of a disagg handoff (prefill + first token is
# bounded work, unlike decode): a replica whose engine WEDGES without dying
# raises nothing, and an unbounded get here would pin a handoff-pool thread
# forever — 32 such requests would starve every disagg call on this router.
# On timeout the request falls back to colocated recompute (greedy-identical);
# matches the core plane's 300s stream timeout.
_PREFILL_HANDOFF_TIMEOUT_S = 300.0


def _is_replica_failure(e: BaseException) -> bool:
    """True for infrastructure failures (replica killed/crashed between
    router refreshes) — retryable on another replica; user-code exceptions
    are not."""
    try:
        from ..core.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
            TaskError,
            WorkerCrashedError,
        )
    except Exception:  # noqa: BLE001
        return False
    kinds = (ActorDiedError, ActorUnavailableError, WorkerCrashedError)
    if isinstance(e, kinds):
        return True
    return isinstance(e, TaskError) and isinstance(
        getattr(e, "cause", None), kinds
    )


def _routing_prompt(args, kwargs) -> Optional[List[int]]:
    """Best-effort token-id prompt extraction for prefix-affinity routing:
    `generate(prompt, ...)` style calls carry it as the first positional or
    a `prompt=` kwarg; HTTP ingress carries it in the request body. Returns
    None (→ load-based routing) for anything that doesn't look like token
    ids — routing must never fail a call."""
    p = kwargs.get("prompt")
    if p is None and args:
        a0 = args[0]
        if isinstance(a0, (list, tuple)):
            p = a0
        else:
            j = getattr(a0, "json", None)  # HTTP Request-like
            if callable(j):
                try:
                    body = j()
                    if isinstance(body, dict):
                        p = body.get("prompt")
                except Exception:  # noqa: BLE001
                    p = None
    if isinstance(p, (list, tuple)) and p and not isinstance(
        p[0], (str, bytes, list, tuple, dict)
    ):
        try:
            int(p[0])
        except (TypeError, ValueError):
            return None
        return list(p)
    return None


class DeploymentResponse:
    """Future-like result of `handle.method.remote()` (reference
    `serve/handle.py` DeploymentResponse)."""

    def __init__(self, ref=None, future=None, on_done=None, retry=None):
        self._ref = ref
        self._future = future
        self._on_done = on_done
        # One-shot failover: on a REPLICA failure (not a user exception),
        # re-route the call through the router once (`Router.call` wires
        # this up for unary calls).
        self._retry = retry

    def result(self, timeout_s: Optional[float] = None):
        import ray_tpu

        try:
            if self._future is not None:
                ref = self._future.result(timeout_s)
                if isinstance(ref, Exception):
                    raise ref
                return ref
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except Exception as e:  # noqa: BLE001
            retry, self._retry = self._retry, None
            if retry is not None and _is_replica_failure(e):
                return retry(timeout_s)
            raise
        finally:
            if self._on_done is not None:
                self._on_done()
                self._on_done = None

    def __del__(self):
        # Fire-and-forget callers never invoke result(); release the
        # router's outstanding-count slot when the response is dropped.
        if self._on_done is not None:
            try:
                self._on_done()
            except Exception:  # noqa: BLE001
                pass

    def _to_object_ref(self):
        if self._ref is None:
            raise RuntimeError("Batched responses have no single ObjectRef")
        return self._ref


class DeploymentResponseGenerator:
    """Iterate chunks of a streaming deployment call (reference:
    `serve.handle.DeploymentResponseGenerator`). `direct_gen` carries an
    already-materialized chunk generator instead of an ObjectRef stream —
    the disaggregated handoff path yields tokens from two replicas'
    streams behind one facade."""

    def __init__(self, ref_generator, on_done=None, direct_gen=None):
        self._gen = ref_generator
        self._on_done = on_done
        self._direct = direct_gen

    def __iter__(self):
        import ray_tpu

        try:
            if self._direct is not None:
                yield from self._direct
                return
            for ref in self._gen:
                yield ray_tpu.get(ref)
        finally:
            if self._on_done is not None:
                self._on_done()
                self._on_done = None


class _Batcher:
    """Router-side batch former for one (deployment, method)."""

    def __init__(self, router: "Router", method: str, max_batch_size: int, wait_s: float):
        self.router = router
        self.method = method
        self.max_batch_size = max_batch_size
        self.wait_s = wait_s
        self._lock = threading.Lock()
        self._pending: List[Tuple[Any, Any, str]] = []  # (arg, Future, model_id)
        self._timer: Optional[threading.Timer] = None

    def submit(self, arg: Any, model_id: str):
        from concurrent.futures import Future

        fut = Future()
        flush_now = False
        with self._lock:
            self._pending.append((arg, fut, model_id))
            if len(self._pending) >= self.max_batch_size:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(self.wait_s, self._flush)
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush()
        return DeploymentResponse(future=fut)

    def _flush(self):
        while True:
            with self._lock:
                if self._timer is not None:
                    self._timer.cancel()
                    self._timer = None
                # At most max_batch_size per dispatch: a submit racing
                # between the caller's flush decision and this lock could
                # otherwise overfill the batch (observed: 9 items reaching a
                # max_batch_size=8 replica, which had shaped its jit program
                # for exactly 8).
                pending = self._pending[: self.max_batch_size]
                del self._pending[: self.max_batch_size]
                leftover = len(self._pending)
                if 0 < leftover < self.max_batch_size and self._timer is None:
                    self._timer = threading.Timer(self.wait_s, self._flush)
                    self._timer.daemon = True
                    self._timer.start()
            if not pending:
                return
            # Split by model_id (multiplexed batches must be homogeneous).
            by_model: Dict[str, List[Tuple[Any, Any]]] = {}
            for arg, fut, mid in pending:
                by_model.setdefault(mid, []).append((arg, fut))
            for mid, items in by_model.items():
                args = [a for a, _ in items]
                futs = [f for _, f in items]
                try:
                    results = self.router.call_batch(self.method, args, mid)
                    for f, r in zip(futs, results):
                        f.set_result(r)
                except Exception as e:  # noqa: BLE001
                    for f in futs:
                        f.set_result(e)
            if leftover < self.max_batch_size:
                return  # partial remainder waits out its timer


class Router:
    """One per (process, app, deployment)."""

    _routers: Dict[Tuple[str, str], "Router"] = {}
    _routers_lock = threading.Lock()

    @classmethod
    def get_or_create(cls, app_name: str, deployment_name: str) -> "Router":
        key = (app_name, deployment_name)
        with cls._routers_lock:
            r = cls._routers.get(key)
            if r is None:
                r = cls._routers[key] = Router(app_name, deployment_name)
            return r

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._lock = threading.Lock()
        self._info: Optional[Dict] = None
        self._last_refresh = 0.0
        self._outstanding: Dict[int, int] = {}  # replica idx -> in-flight
        self._batchers: Dict[str, _Batcher] = {}
        self._reported_t = 0.0
        # Disaggregated handoff orchestration runs off-thread (two
        # sequential replica RPCs per request must not block the caller's
        # .remote()). Created lazily — colocated fleets never pay for it.
        self._handoff_pool = None
        # Stable identity for controller-side metrics: outstanding counts
        # are keyed per router and SUMMED across routers (EMA-blending
        # different routers into one stream undercounted the fleet).
        self._router_id = uuid.uuid4().hex[:12]

    # ------------------------------------------------------------ snapshot
    def _controller(self):
        import ray_tpu
        from .controller import CONTROLLER_NAME, SERVE_NAMESPACE

        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)

    def _refresh(self, force: bool = False):
        import ray_tpu

        now = time.monotonic()
        with self._lock:
            stale = force or self._info is None or now - self._last_refresh > _ROUTER_REFRESH_S
        if not stale:
            return
        try:
            info = ray_tpu.get(
                self._controller().get_deployment_info.remote(self.app_name, self.deployment_name)
            )
        except Exception:  # noqa: BLE001 — controller/head unreachable
            # Head-failover survivability: replica handles route DIRECTLY
            # (actor channels never touch the head on the hot path), so a
            # router holding ANY snapshot keeps answering on it through
            # the outage. The refresh clock is advanced so a dying head is
            # probed once per refresh window, not per request; the next
            # successful refresh re-resolves the controller and re-enters
            # the telemetry/report loop. With no snapshot at all there is
            # nothing to serve from — surface the failure.
            with self._lock:
                if self._info is not None:
                    self._last_refresh = now
                    return
            raise
        if info is None:
            raise RuntimeError(
                f"Deployment {self.deployment_name} in app {self.app_name} not found"
            )
        with self._lock:
            self._info = info
            self._last_refresh = now
            self._outstanding = {i: self._outstanding.get(i, 0) for i in range(len(info["replicas"]))}

    def _replica_roles(self) -> List[Optional[str]]:
        """Per-replica pool role, controller-assigned role first (available
        the moment a replica is routable) with engine telemetry as the
        fallback. Called under self._lock."""
        info = self._info
        roles = list(info.get("replica_roles") or [])
        metas = info.get("replica_meta") or []
        out: List[Optional[str]] = []
        for i in range(len(info["replicas"])):
            r = roles[i] if i < len(roles) else None
            if not r and i < len(metas) and metas[i]:
                r = metas[i].get("role")
                r = r if r in ("prefill", "decode") else None
            out.append(r)
        return out

    def _pick_replica(
        self,
        model_id: str = "",
        prompt: Optional[List[int]] = None,
        exclude: Optional[int] = None,
        role: Optional[str] = None,
    ) -> Tuple[int, Any, str]:
        """Returns (index, replica handle, replica tag) — the tag is read
        under the same lock as the pick, so failover bookkeeping can't be
        torn by a concurrent refresh reordering the replica list. With
        `role`, candidates are restricted to that pool (falling back to the
        whole fleet when the pool is empty — a half-dead disaggregated
        deployment degrades to colocated serving, never to an error)."""
        self._refresh()
        with self._lock:
            replicas = self._info["replicas"]
            if not replicas:
                raise RuntimeError(f"No replicas for {self.deployment_name}")
            n = len(replicas)
            tags = self._info["replica_tags"]
            candidates = [i for i in range(n) if i != exclude] or list(range(n))
            if role is not None:
                from .fleet import split_pools

                pre, dec = split_pools(self._replica_roles())
                pool = pre if role == "prefill" else dec
                pool = [i for i in pool if i in set(candidates)]
                candidates = pool or candidates
            if model_id:
                # Rendezvous hash → cache-affine replica for multiplexed
                # models (same construction as the fleet plane's cold-prefix
                # convergence).
                from .fleet import rendezvous_rank

                idx = max(
                    candidates,
                    key=lambda i: rendezvous_rank(model_id, tags[i]),
                )
            elif len(candidates) == 1:
                idx = candidates[0]
            else:
                idx = self._pick_fleet(candidates, prompt)
            self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
            return idx, replicas[idx], tags[idx]

    def _pick_fleet(self, candidates: List[int], prompt) -> int:
        """Prefix-affinity placement (`serve/fleet/routing.py`): hash the
        prompt's leading full KV blocks (the engine's own content-hash
        chain) and steer to the replica whose advertised hot-prefix digest
        matches deepest; cold prefixes converge by rendezvous, saturated or
        telemetry-less fleets degrade to power-of-two on load. Called under
        self._lock.

        Affinity engages only once SOME replica has reported engine
        telemetry — a deployment that never reports one (plain non-LLM
        classes whose methods happen to take numeric lists) keeps plain
        power-of-two load spreading. The controller captures telemetry on
        the same reconcile pass that PROMOTES a replica, so an LLM fleet
        has it from the moment `serve.run` returns; if a replica's report
        predates block_size (older engine), a default keeps cold routing
        deterministic."""
        info = self._info
        metas = info.get("replica_meta") or []
        chain: List[str] = []
        if (
            prompt is not None
            and info.get("prefix_affinity", True)
            and any(metas)
        ):
            bs = next(
                (m.get("block_size") for m in metas if m and m.get("block_size")),
                0,
            ) or _DEFAULT_ROUTING_BLOCK
            from .fleet import routing_chain

            chain = routing_chain(prompt, bs)
        if chain or any(m for m in metas):
            from .fleet import pick_replica as _fleet_pick

            tags = info["replica_tags"]
            spill = max(int(info.get("max_ongoing_requests") or 8), 1)
            idx, _reason = _fleet_pick(
                chain,
                [tags[i] for i in candidates],
                [metas[i] if i < len(metas) else None for i in candidates],
                {
                    j: self._outstanding.get(i, 0)
                    for j, i in enumerate(candidates)
                },
                spill,
            )
            return candidates[idx]
        # No telemetry at all: power of two choices on local outstanding.
        a, b = random.sample(candidates, 2)
        return (
            a
            if self._outstanding.get(a, 0) <= self._outstanding.get(b, 0)
            else b
        )

    def _done(self, idx: int):
        with self._lock:
            self._outstanding[idx] = max(self._outstanding.get(idx, 1) - 1, 0)

    def _maybe_report_metrics(self):
        now = time.monotonic()
        if now - self._reported_t < 1.0:
            return
        self._reported_t = now
        try:
            total = sum(self._outstanding.values())
            self._controller().record_request_metrics.remote(
                self.app_name, self.deployment_name, float(total),
                self._router_id,
            )
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------- disaggregated calls
    def _disagg_plan(
        self, method: str, args, kwargs, prompt: Optional[List[int]]
    ) -> Optional[Dict]:
        """(prompt, max_new_tokens, eos) when this call should ride the
        prefill->handoff->decode path: an LLM generation method, a token
        prompt, and BOTH pools present. None keeps the colocated path."""
        if prompt is None or method not in ("generate", "generate_stream",
                                            "__call__"):
            return None
        with self._lock:
            if self._info is None or not self._info.get("prefill_replicas"):
                return None  # colocated deployment: pay nothing per call
            from .fleet import split_pools

            pre, dec = split_pools(self._replica_roles())
            if not pre or not dec:
                return None
        max_new, eos = 16, None
        try:
            if method == "__call__":
                body = args[0].json() if hasattr(args[0], "json") else args[0]
                if not isinstance(body, dict):
                    return None
                max_new = int(body.get("max_new_tokens", 16))
                eos = body.get("eos_token")
            else:
                if len(args) > 1:
                    max_new = int(args[1])
                elif "max_new_tokens" in kwargs:
                    max_new = int(kwargs["max_new_tokens"])
                if len(args) > 2:
                    eos = args[2]
                else:
                    eos = kwargs.get("eos_token")
        except Exception:  # noqa: BLE001 — unparseable: keep colocated path
            return None
        # Captured on the CALLER's thread — the handoff pool thread that
        # executes the plan has no task context, so the trace id must ride
        # the plan dict for one x-request-id to cover the whole handoff.
        return {"prompt": list(prompt), "max_new": max_new, "eos": eos,
                "trace": tracing.get_trace_id()}

    def _colocated_fallback(self, plan: Dict, exclude_tag: Optional[str],
                            timeout_s=None) -> Dict:
        """Full recompute on one replica (decode pool preferred — its lanes
        are the scarce resource a dead prefill replica leaves idle): the
        degraded mode for ANY disagg failure, identical greedy output."""
        import ray_tpu

        self._refresh(force=True)
        with self._lock:
            tags = self._info["replica_tags"]
            ex = tags.index(exclude_tag) if exclude_tag in tags else None
        idx, rep, _ = self._pick_replica(
            prompt=plan["prompt"], exclude=ex, role="decode"
        )
        try:
            return ray_tpu.get(
                rep.handle_request.remote(
                    "generate",
                    (plan["prompt"], plan["max_new"], plan["eos"]), {},
                ),
                timeout=timeout_s,
            )
        finally:
            self._done(idx)

    def _disagg_prefill(self, plan: Dict) -> Tuple[Optional[Dict], Optional[Dict]]:
        """Run the prefill half on the prefill pool. Returns
        (prefill_result, finished_response): exactly one is non-None —
        a finished_response means the request completed (first token was
        the whole generation, or the prefill replica died and the
        colocated fallback answered)."""
        import ray_tpu

        idx, rep, tag = self._pick_replica(
            prompt=plan["prompt"], role="prefill"
        )
        trace = plan.get("trace")
        flow = f"disagg/{trace}" if trace else None
        t0 = flight.now_ns()
        try:
            res = ray_tpu.get(
                rep.handle_request.remote(
                    "prefill_handoff",
                    (plan["prompt"], plan["max_new"], plan["eos"]), {},
                ),
                timeout=_PREFILL_HANDOFF_TIMEOUT_S,
            )
        except Exception as e:  # noqa: BLE001
            if not (_is_replica_failure(e)
                    or isinstance(e, ray_tpu.GetTimeoutError)):
                raise
            # Prefill replica died (or wedged) mid-handoff: recompute
            # elsewhere. Nothing imports a descriptor for THIS request —
            # the fallback recomputes from scratch, greedy-identical.
            # Death-kind span: exempt from the flight ring cap, so the
            # partial trace stays readable after a SIGKILL'd replica.
            flight.record(
                "disagg.prefill_abort", t0, flight.now_ns(), trace=trace,
                lane="serve/router", kind="death", flow=flow,
                attrs={"replica": tag, "error": type(e).__name__})
            return None, self._colocated_fallback(plan, tag)
        finally:
            self._done(idx)
        flight.record(
            "disagg.prefill_handoff", t0, flight.now_ns(), trace=trace,
            lane="serve/router", flow=flow, attrs={"replica": tag})
        if res.get("finished"):
            return None, {"tokens": res["tokens"],
                          "finish_reason": res["finish_reason"]}
        return res, None

    def _disagg_call(self, plan: Dict) -> Dict:
        """Unary prefill->handoff->decode orchestration (runs on the
        handoff pool thread). Greedy-deterministic at every fallback, so
        the response is token-for-token the colocated response no matter
        which replicas survive."""
        import ray_tpu

        # Re-install the caller's trace id on this pool thread so the
        # replica RPCs (and their engine spans) inherit it.
        tracing.set_trace_id(plan.get("trace"))
        res, done = self._disagg_prefill(plan)
        if done is not None:
            return done
        first = res["tokens"][0]
        idx, rep, tag = self._pick_replica(role="decode")
        trace = plan.get("trace")
        t0 = flight.now_ns()
        try:
            rest = ray_tpu.get(
                rep.handle_request.remote(
                    "decode_imported",
                    (plan["prompt"], first, plan["max_new"] - 1, plan["eos"],
                     res.get("descriptor")), {},
                )
            )
        except Exception as e:  # noqa: BLE001
            if not _is_replica_failure(e):
                raise
            flight.record(
                "disagg.decode_abort", t0, flight.now_ns(), trace=trace,
                lane="serve/router", kind="death",
                attrs={"replica": tag, "error": type(e).__name__})
            return self._colocated_fallback(plan, tag)
        finally:
            self._done(idx)
        flight.record(
            "disagg.decode", t0, flight.now_ns(), trace=trace,
            lane="serve/router",
            flow=f"disagg/{trace}" if trace else None,
            attrs={"replica": tag})
        return {"tokens": [first] + rest["tokens"],
                "finish_reason": rest["finish_reason"]}

    def _disagg_response(self, plan: Dict) -> DeploymentResponse:
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            if self._handoff_pool is None:
                self._handoff_pool = ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="rtpu-handoff"
                )
        self._maybe_report_metrics()
        return DeploymentResponse(
            future=self._handoff_pool.submit(self._disagg_call, plan)
        )

    def _disagg_stream_gen(self, plan: Dict):
        """Streaming orchestration: yield the prefill replica's first token
        as soon as it lands (disaggregation's whole point: TTFT decoupled
        from decode load), then the decode replica's stream. Greedy
        determinism makes mid-stream failover exact: recompute colocated
        and skip what was already yielded — no wedged stream, no
        duplicated or diverging tokens."""
        import ray_tpu

        tracing.set_trace_id(plan.get("trace"))
        res, done = self._disagg_prefill(plan)
        if done is not None:
            yield from done["tokens"]
            return
        first = res["tokens"][0]
        yield first
        emitted = 1
        idx, rep, tag = self._pick_replica(role="decode")
        try:
            gen = rep.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(
                "decode_imported_stream",
                (plan["prompt"], first, plan["max_new"] - 1, plan["eos"]),
                {"descriptor": res.get("descriptor")},
            )
            for ref in gen:
                tok = ray_tpu.get(ref)
                yield tok
                emitted += 1
        except Exception as e:  # noqa: BLE001
            if not _is_replica_failure(e):
                raise
            fb = self._colocated_fallback(plan, tag)
            yield from fb["tokens"][emitted:]
        finally:
            self._done(idx)

    # ---------------------------------------------------------------- calls
    def call(self, method: str, args, kwargs, model_id: str = "") -> DeploymentResponse:
        self._refresh()
        batch_cfg = self._info["batch_methods"].get(method)
        if batch_cfg is not None:
            if kwargs or len(args) != 1:
                raise ValueError(
                    f"@serve.batch method {method} takes exactly one positional arg"
                )
            batcher = self._batchers.get(method)
            if batcher is None:
                batcher = self._batchers[method] = _Batcher(
                    self, method, batch_cfg["max_batch_size"], batch_cfg["batch_wait_timeout_s"]
                )
            self._maybe_report_metrics()
            return batcher.submit(args[0], model_id)

        prompt = _routing_prompt(args, kwargs)
        if not model_id:
            plan = self._disagg_plan(method, args, kwargs, prompt)
            if plan is not None:
                return self._disagg_response(plan)
        idx, replica, failed_tag = self._pick_replica(model_id, prompt=prompt)
        try:
            ref = replica.handle_request.remote(method, args, kwargs, model_id)
        except Exception:
            self._done(idx)
            raise
        self._maybe_report_metrics()

        def retry(timeout_s):
            # The replica died between refreshes: force a state refresh and
            # re-route ONCE to a different replica instead of surfacing the
            # dead-handle error to the caller.
            import ray_tpu

            self._refresh(force=True)
            with self._lock:
                t2 = self._info["replica_tags"]
                ex = t2.index(failed_tag) if failed_tag in t2 else None
            i2, r2, _ = self._pick_replica(model_id, prompt=prompt, exclude=ex)
            try:
                return ray_tpu.get(
                    r2.handle_request.remote(method, args, kwargs, model_id),
                    timeout=timeout_s,
                )
            finally:
                self._done(i2)

        # Outstanding count drops when the caller consumes the result.
        return DeploymentResponse(
            ref=ref, on_done=lambda: self._done(idx), retry=retry
        )

    def call_streaming(
        self, method: str, args, kwargs, model_id: str = ""
    ) -> "DeploymentResponseGenerator":
        """Streaming call: chunks arrive as the replica's generator yields
        (reference: `handle.options(stream=True)` →
        ObjectRefGenerator-backed responses)."""
        self._refresh()
        prompt = _routing_prompt(args, kwargs)
        if not model_id:
            plan = self._disagg_plan(method, args, kwargs, prompt)
            if plan is not None:
                self._maybe_report_metrics()
                return DeploymentResponseGenerator(None, direct_gen=self._disagg_stream_gen(plan))
        idx, replica, _ = self._pick_replica(
            model_id, prompt=prompt
        )
        try:
            gen = getattr(replica, "handle_request_streaming").options(
                num_returns="streaming"
            ).remote(method, args, kwargs, model_id)
        except Exception:
            self._done(idx)
            raise
        self._maybe_report_metrics()
        return DeploymentResponseGenerator(gen, on_done=lambda: self._done(idx))

    def call_batch(self, method: str, batched_args: List, model_id: str) -> List:
        import ray_tpu

        idx, replica, _ = self._pick_replica(model_id)
        try:
            return ray_tpu.get(
                replica.handle_batch.remote(method, batched_args, model_id)
            )
        except Exception:
            self._refresh(force=True)
            raise
        finally:
            self._done(idx)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    """Serializable reference to a deployment; composable across replicas
    (reference `serve/handle.py:827`)."""

    def __init__(
        self,
        app_name: str,
        deployment_name: str,
        multiplexed_model_id: str = "",
        stream: bool = False,
    ):
        self._app_name = app_name
        self._deployment_name = deployment_name
        self._model_id = multiplexed_model_id
        self._stream = stream

    def options(
        self,
        *,
        multiplexed_model_id: Optional[str] = None,
        stream: Optional[bool] = None,
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self._app_name,
            self._deployment_name,
            multiplexed_model_id if multiplexed_model_id is not None else self._model_id,
            self._stream if stream is None else stream,
        )

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        # Resolve nested responses/refs before shipping (reference chains
        # DeploymentResponses through the object store).
        args = tuple(
            a.result() if isinstance(a, DeploymentResponse) else a for a in args
        )
        kwargs = {
            k: (v.result() if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        router = Router.get_or_create(self._app_name, self._deployment_name)
        if self._stream:
            return router.call_streaming(method, args, kwargs, self._model_id)
        return router.call(method, args, kwargs, self._model_id)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._app_name, self._deployment_name, self._model_id, self._stream),
        )

    def __repr__(self):
        return f"DeploymentHandle({self._app_name}/{self._deployment_name})"
