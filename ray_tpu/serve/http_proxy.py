"""HTTP proxy actor (reference: `serve/_private/proxy.py:773,1313`).

A ThreadingHTTPServer inside an actor: each HTTP request resolves the route
prefix against the controller's routing snapshot and forwards to the app's
ingress deployment through a DeploymentHandle (same data plane as Python
callers). The reference runs uvicorn; requests here carry a simple `Request`
object with method/path/query/body accessors.
"""

from __future__ import annotations

import itertools
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


from ..util import tracing
from ._common import response_bytes as _as_bytes


class Request:
    """What ingress `__call__` receives for HTTP traffic."""

    def __init__(self, method: str, path: str, query: dict, body: bytes, headers: dict):
        self.method = method
        self.path = path
        self.query_params = query
        self.body = body
        self.headers = headers

    def json(self):
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


class HTTPProxy:
    """NOTE: instantiated as a ray_tpu actor by `serve.start`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._routes = {}
        self._routes_version = -1
        self._routes_refreshed = 0.0
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence per-request stderr noise
                pass

            def _serve(self):
                # One request id per HTTP request; it IS the trace id every
                # downstream hop inherits (handle → replica → engine), so
                # `/api/traces?trace_id=<x-request-id>` shows the whole path.
                rid = tracing.new_trace_id()
                self.request_id = rid
                t0 = time.time()
                status = 500
                try:
                    tracing.set_trace_id(rid)
                except Exception:  # noqa: BLE001 — runtime still booting
                    pass
                try:
                    status, _ = self._serve_traced()
                finally:
                    try:
                        tracing.record_span(
                            "proxy.request", t0, time.time() - t0,
                            trace_id=rid,
                            attrs={"method": self.command, "path": self.path,
                                   "status": status, "request_id": rid},
                        )
                        tracing.set_trace_id(None)
                    except Exception:  # noqa: BLE001
                        pass

            def _serve_traced(self):
                try:
                    status, payload = proxy._handle(self)
                except Exception as e:  # noqa: BLE001
                    status, payload = 500, json.dumps({"error": repr(e)}).encode()
                if callable(payload):
                    # Streaming route: chunked transfer, flushed per chunk as
                    # the replica's generator yields (reference: Serve
                    # StreamingResponse over ASGI). Pull the FIRST chunk
                    # before committing status so a failing generator still
                    # gets a proper 500.
                    it = iter(payload())
                    try:
                        first = next(it, None)
                    except Exception as e:  # noqa: BLE001
                        err = json.dumps({"error": repr(e)}).encode()
                        self.send_response(500)
                        self.send_header("Content-Length", str(len(err)))
                        self.send_header("Content-Type", "application/json")
                        self.send_header("x-request-id", self.request_id)
                        self.end_headers()
                        self.wfile.write(err)
                        return 500, None
                    self.send_response(status)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("x-request-id", self.request_id)
                    self.end_headers()
                    try:
                        chunks = (
                            iter(())
                            if first is None
                            else itertools.chain((first,), it)
                        )
                        for chunk in chunks:
                            data = _as_bytes(chunk)
                            self.wfile.write(
                                f"{len(data):X}\r\n".encode() + data + b"\r\n"
                            )
                            self.wfile.flush()
                        self.wfile.write(b"0\r\n\r\n")
                    except BrokenPipeError:
                        self.close_connection = True
                    except Exception:  # noqa: BLE001 — mid-stream failure:
                        # abort the chunked body AND close the socket (like
                        # ASGI servers) so the client unblocks; a kept-alive
                        # connection would leave it waiting mid-body forever.
                        self.close_connection = True
                    return status, None
                self.send_response(status)
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("Content-Type", "application/json")
                self.send_header("x-request-id", self.request_id)
                self.end_headers()
                self.wfile.write(payload)
                return status, None

            do_GET = do_POST = do_PUT = do_DELETE = _serve

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def get_port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "ok"

    def _refresh_routes(self):
        import ray_tpu
        from .controller import CONTROLLER_NAME, SERVE_NAMESPACE

        now = time.monotonic()
        if now - self._routes_refreshed < 1.0 and self._routes:
            return
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        self._routes = ray_tpu.get(controller.routing_snapshot.remote())
        self._routes_refreshed = now

    def _handle(self, h: BaseHTTPRequestHandler):
        from .handle import DeploymentHandle

        # Drain the body FIRST — an early return with unread body bytes
        # corrupts the next request on a keep-alive connection.
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else b""

        self._refresh_routes()
        parsed = urlparse(h.path)
        path = parsed.path
        match: Optional[str] = None
        for prefix in sorted(self._routes, key=len, reverse=True):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                match = prefix
                break
        if match is None:
            return 404, json.dumps({"error": f"no route for {path}"}).encode()
        route = self._routes[match]
        req = Request(
            method=h.command,
            path=path[len(match.rstrip("/")):] or "/",
            query={k: v[0] if len(v) == 1 else v for k, v in parse_qs(parsed.query).items()},
            body=body,
            headers=dict(h.headers),
        )
        if route.get("streaming"):
            handle = DeploymentHandle(route["app"], route["ingress"], stream=True)
            gen = handle.remote(req)
            return 200, lambda: iter(gen)

        handle = DeploymentHandle(route["app"], route["ingress"])
        result = handle.remote(req).result(timeout_s=60.0)

        if isinstance(result, bytes):
            return 200, result
        if isinstance(result, str):
            return 200, result.encode()
        return 200, json.dumps(result).encode()

    def shutdown(self):
        self._server.shutdown()
