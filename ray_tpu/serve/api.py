"""serve public API (reference: `python/ray/serve/api.py`: `start`, `run:449`,
`delete`, `status`, `shutdown`, `get_app_handle`, `get_deployment_handle`)."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import cloudpickle

from .controller import CONTROLLER_NAME, SERVE_NAMESPACE, ServeController
from .deployment import Application, AutoscalingConfig, Deployment
from .handle import DeploymentHandle, Router

_http_proxy = None
_grpc_proxy = None


def _ensure_ray():
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    return ray_tpu


def _get_controller(create: bool = True):
    ray = _ensure_ray()
    handle = ray.get_actor_or_none(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    if handle is None and create:
        handle = (
            ray.remote(ServeController)
            .options(name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
            .remote()
        )
        ray.get(handle.ping.remote())
    return handle


def start(
    detached: bool = True,
    http_options: Optional[dict] = None,
    grpc_options: Optional[dict] = None,
    **_compat,
):
    """Start the Serve control plane (+ HTTP / gRPC proxies if configured)."""
    global _http_proxy, _grpc_proxy
    ray = _ensure_ray()
    _get_controller()
    if http_options and _http_proxy is None:
        from .http_proxy import HTTPProxy

        _http_proxy = ray.remote(HTTPProxy).remote(
            http_options.get("host", "127.0.0.1"), http_options.get("port", 0)
        )
        ray.get(_http_proxy.ping.remote())
    if grpc_options and _grpc_proxy is None:
        from .grpc_proxy import GRPCProxy

        _grpc_proxy = ray.remote(GRPCProxy).remote(
            grpc_options.get("host", "127.0.0.1"), grpc_options.get("port", 0)
        )
        ray.get(_grpc_proxy.ping.remote())
    return _http_proxy


def http_port() -> Optional[int]:
    ray = _ensure_ray()
    if _http_proxy is None:
        return None
    return ray.get(_http_proxy.get_port.remote())


def grpc_port() -> Optional[int]:
    ray = _ensure_ray()
    if _grpc_proxy is None:
        return None
    return ray.get(_grpc_proxy.get_port.remote())


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: str = "/",
    _blocking: bool = True,
    timeout_s: float = 60.0,
) -> DeploymentHandle:
    import dataclasses

    ray = _ensure_ray()
    controller = _get_controller()

    apps = app._flatten()
    specs = []
    for a in apps:
        dep: Deployment = a.deployment
        init_args = tuple(
            DeploymentHandle(name, x.deployment.name) if isinstance(x, Application) else x
            for x in a.init_args
        )
        init_kwargs = {
            k: DeploymentHandle(name, v.deployment.name) if isinstance(v, Application) else v
            for k, v in a.init_kwargs.items()
        }
        opts = dataclasses.asdict(dep.opts)
        batch_methods = {}
        if isinstance(dep._callable, type):
            for mname in dir(dep._callable):
                m = getattr(dep._callable, mname, None)
                cfg = getattr(m, "_serve_batch_config", None)
                if cfg is not None:
                    batch_methods[mname] = {
                        "max_batch_size": cfg.max_batch_size,
                        "batch_wait_timeout_s": cfg.batch_wait_timeout_s,
                    }
        specs.append(
            {
                "name": dep.name,
                "cls": cloudpickle.dumps(dep._callable),
                "init_args": cloudpickle.dumps((init_args, init_kwargs)),
                "opts": opts,
                "batch_methods": batch_methods,
            }
        )

    ingress_name = app.deployment.name
    # Streaming ingress: a generator-function __call__ makes the HTTP proxy
    # stream chunks as they are produced (reference: Serve StreamingResponse).
    import inspect as _inspect

    ingress_callable = app.deployment._callable
    ingress_fn = (
        getattr(ingress_callable, "__call__", None)
        if isinstance(ingress_callable, type)
        else ingress_callable
    )
    ingress_streaming = bool(
        ingress_fn is not None and _inspect.isgeneratorfunction(ingress_fn)
    )
    ray.get(
        controller.deploy_application.remote(
            name, specs, route_prefix, ingress_name, ingress_streaming
        )
    )
    if _blocking:
        _wait_healthy(name, timeout_s)
    # Invalidate any cached routers for this app (replica sets changed).
    with Router._routers_lock:
        for key in list(Router._routers):
            if key[0] == name:
                del Router._routers[key]
    return DeploymentHandle(name, ingress_name)


def _wait_healthy(app_name: str, timeout_s: float):
    ray = _ensure_ray()
    controller = _get_controller()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = ray.get(controller.status.remote())
        app = st.get(app_name)
        if app and app["status"] == "RUNNING":
            return
        time.sleep(0.1)
    raise TimeoutError(f"Application {app_name} failed to become RUNNING in {timeout_s}s")


def run_config(config: dict) -> Dict[str, DeploymentHandle]:
    """Deploy applications from a declarative config (reference: Serve's
    REST schema `serve/schema.py` + `serve deploy config.yaml`).

    Schema:
        {"http_options": {"host": ..., "port": ...},           # optional
         "applications": [
             {"name": "app", "route_prefix": "/",
              "import_path": "my_module:app",                  # Application
              "deployments": [                                 # overrides
                  {"name": "Model", "num_replicas": 2,
                   "user_config": {...}}]}]}
    """
    import importlib

    from .deployment import Application

    if config.get("http_options"):
        start(http_options=config["http_options"])
    handles: Dict[str, DeploymentHandle] = {}
    for app_cfg in config.get("applications", []):
        mod_name, _, attr = app_cfg["import_path"].partition(":")
        target = getattr(importlib.import_module(mod_name), attr)
        app = target() if callable(target) and not isinstance(target, Application) else target
        if not isinstance(app, Application):
            raise TypeError(
                f"{app_cfg['import_path']} is not a bound Application "
                "(expected `deployment.bind(...)` or a zero-arg builder)"
            )
        overrides = {d["name"]: d for d in app_cfg.get("deployments", [])}
        # Apply overrides to the (module-cached) graph, deploy, then RESTORE:
        # a later run_config without the override must see the code defaults,
        # not this config's leftovers.
        originals = [(node, node.deployment) for node in app._flatten()]
        try:
            for node, dep in originals:
                o = overrides.get(dep.name)
                if o:
                    node.deployment = dep.options(
                        **{k: v for k, v in o.items() if k != "name"}
                    )
            name = app_cfg.get("name", "default")
            handles[name] = run(
                app,
                name=name,
                route_prefix=app_cfg.get("route_prefix", "/"),
            )
        finally:
            for node, dep in originals:
                node.deployment = dep
    return handles


def delete(name: str, _blocking: bool = True):
    ray = _ensure_ray()
    controller = _get_controller(create=False)
    if controller is not None:
        ray.get(controller.delete_application.remote(name))


def status() -> Dict[str, Any]:
    ray = _ensure_ray()
    controller = _get_controller(create=False)
    if controller is None:
        return {"applications": {}}
    return {"applications": ray.get(controller.status.remote())}


def get_app_handle(name: str) -> DeploymentHandle:
    ray = _ensure_ray()
    controller = _get_controller(create=False)
    if controller is None:
        raise RuntimeError("Serve is not running")
    st = ray.get(controller.status.remote())
    if name not in st:
        raise ValueError(f"Application {name} not found")
    ingress = ray.get(controller.routing_snapshot.remote())
    # Find ingress by matching app name in snapshot, else ask status.
    for route, info in ingress.items():
        if info["app"] == name:
            return DeploymentHandle(name, info["ingress"])
    raise ValueError(f"Application {name} has no ingress")


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def shutdown():
    """Tear down all applications, the controller and proxies."""
    global _http_proxy, _grpc_proxy
    ray = _ensure_ray()
    controller = _get_controller(create=False)
    if controller is not None:
        try:
            ray.get(controller.shutdown.remote())
            ray.kill(controller)
        except Exception:  # noqa: BLE001
            pass
    if _http_proxy is not None:
        try:
            ray.get(_http_proxy.shutdown.remote())
            ray.kill(_http_proxy)
        except Exception:  # noqa: BLE001
            pass
        _http_proxy = None
    if _grpc_proxy is not None:
        try:
            ray.get(_grpc_proxy.shutdown.remote())
            ray.kill(_grpc_proxy)
        except Exception:  # noqa: BLE001
            pass
        _grpc_proxy = None
    with Router._routers_lock:
        Router._routers.clear()
