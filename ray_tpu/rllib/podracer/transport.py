"""Trajectory transport for the Sebulba plane — whole time-major batches
over the arena + bulk planes.

An actor-gang member finishes a rollout fragment holding a dict of [T, N]
numpy arrays. Instead of pickling the dict through an RPC return (double
copy through the driver) it lands the WHOLE batch as ONE first-class arena
object: every array travels as an out-of-band pickle-5 buffer inside one
packed frame (`put_serialized` — the PR 8 span layout), and only a tiny
descriptor rides the actor's RPC reply. The learner imports by rung:

  1. inline — small fragments stay in the descriptor itself;
  2. same-node — the learner deserializes straight off the arena mapping
     (`local_store.read`), deep-copies the array views (nothing here may
     outlive the producer's pin), and releases its read pin;
  3. cross-node — `object_sources` resolves a live copy and ONE
     `bulk.fetch_span_bytes` pull lands the whole frame (span = the full
     object), which `serialization.unpack` opens without further copies;
  4. no rung left -> loud RuntimeError; the supervisor owns the failure.

Pinning contract (same as mpmd.transport): the producer holds each
published batch's ref until its NEXT publish on the same edge — by then the
learner has imported (the driver sequences collect -> update -> collect).

`stats` records which rung every publish/fetch took so the chaos/bench
tests can assert trajectory frames actually ride arena segments instead of
trusting size thresholds.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np

DEFAULT_INLINE_MAX = 64 * 1024


def _rebuild(dtype_str: str, shape, buf) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)


class _OOBLeaf:
    """Array wrapper whose bytes travel as one out-of-band pickle-5 buffer
    (single-tensor analog in mpmd.transport; here one per batch column)."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray):
        self.arr = arr

    def __reduce__(self):
        return (
            _rebuild,
            (self.arr.dtype.str, self.arr.shape, pickle.PickleBuffer(self.arr)),
        )


def _wrap(batch: Dict[str, Any]) -> Dict[str, Any]:
    return {
        k: _OOBLeaf(np.ascontiguousarray(v)) if isinstance(v, np.ndarray) else v
        for k, v in batch.items()
    }


class TrajTransport:
    """Publish/fetch of one trajectory-batch dict over the arena + bulk
    planes."""

    def __init__(
        self,
        inline_max_bytes: int = DEFAULT_INLINE_MAX,
        timeout_s: float = 60.0,
    ):
        self.inline_max = int(inline_max_bytes)
        self.timeout_s = timeout_s
        self.stats = {
            "pub_inline": 0, "pub_arena": 0,
            "fetch_inline": 0, "fetch_local": 0, "fetch_span": 0,
        }
        self._pin = None  # previous publish's ref, held until the next one

    # ----------------------------------------------------------- producer
    def publish(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Land `batch` on the arena, return the descriptor to ship. The
        previous publish's pin is dropped here — the driver's sequencing
        (update(i) completes before collect(i+1) starts) guarantees the
        learner imported it."""
        from ...core import api, serialization, store

        rt = api._global_runtime()
        backend = rt.backend if rt is not None else None
        put_serialized = getattr(backend, "put_serialized", None)
        nbytes = sum(
            v.nbytes for v in batch.values() if isinstance(v, np.ndarray)
        )
        # Below the store's own inline threshold put_serialized lands the
        # frame on the INLINE plane (no shared-store name, nothing for
        # fetch() to read) — such batches must stay in the RPC reply.
        inline_floor = max(self.inline_max, store.INLINE_THRESHOLD)
        if (
            put_serialized is None
            or nbytes <= inline_floor
            or getattr(backend, "remote_client", False)
        ):
            self._pin = None
            self.stats["pub_inline"] += 1
            return {"inline": batch}
        payload, buffers = serialization.serialize(_wrap(batch))
        try:
            task_hex = rt.current_task_id.hex()
        except Exception:  # noqa: BLE001 — outside a task context
            self._pin = None
            self.stats["pub_inline"] += 1
            return {"inline": batch}
        frame_len = serialization.packed_size(payload, buffers)
        ref, name, span_ok = put_serialized(payload, buffers, task_hex)
        if name is None:  # landed inline/remote after all (threshold drift)
            self._pin = None
            self.stats["pub_inline"] += 1
            return {"inline": batch}
        self._pin = ref  # drops the PREVIOUS ref; holds this one
        self.stats["pub_arena"] += 1
        return {
            "name": name,
            "hex": ref.id.hex(),
            # Span = the WHOLE packed frame: the cross-node import is one
            # bulk pull + unpack, not per-array requests.
            "frame_len": frame_len if span_ok else None,
        }

    # ----------------------------------------------------------- consumer
    def fetch(self, desc: Dict[str, Any]) -> Dict[str, np.ndarray]:
        if "inline" in desc:
            self.stats["fetch_inline"] += 1
            return desc["inline"]
        from ...core import api
        from ...core import bulk as bulk_mod

        backend = api._global_runtime().backend
        name = desc.get("name")
        local_store = getattr(backend, "local_store", None)
        if name and local_store is not None:
            try:
                raw = local_store.read(name)
            except Exception:  # noqa: BLE001 — not on this node / evicted
                pass
            else:
                # Unpacked arrays are views over the producer's arena
                # segment; copy eagerly so nothing outlives its pin, then
                # release our read pin so the producer's drop can free it.
                out = {
                    k: (np.array(v, copy=True) if isinstance(v, np.ndarray)
                        else v)
                    for k, v in raw.items()
                }
                try:
                    local_store.release(name)
                except Exception:  # noqa: BLE001 — release is best-effort
                    pass
                self.stats["fetch_local"] += 1
                return out
        frame_len = desc.get("frame_len")
        sources_of = getattr(backend, "object_sources", None)
        if frame_len is not None and sources_of is not None:
            (src,) = sources_of([desc["hex"]])
            if src:
                from ...core import serialization

                buf = bulk_mod.fetch_span_bytes(
                    src["bulk"], src["name"], 0, frame_len, self.timeout_s
                )
                self.stats["fetch_span"] += 1
                return serialization.unpack(buf)
        raise RuntimeError(
            f"trajectory object {desc.get('hex', '?')} unreachable "
            "(source gone and no span-servable copy) — failing the step for "
            "the gang supervisor"
        )

    def drop_pin(self):
        self._pin = None
