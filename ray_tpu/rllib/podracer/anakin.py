"""Anakin — env dynamics fused into the learner's jit (Podracer §2,
arxiv 2104.06272).

One compiled XLA program per training iteration does EVERYTHING:

    lax.scan over T steps of [B] batched env dynamics
      (policy forward -> action sample -> env.step -> auto-reset)
    -> time-major trajectory, entirely device-resident
    -> the algorithm's update program (for PPO: in-jit GAE via
       `utils/gae.compute_gae`, epoch loop, minibatch permutation,
       clipped-surrogate loss, optimizer — `make_ppo_update` unchanged)

Sampling therefore costs ZERO Python per env step — the Python side
dispatches one call per iteration and reads back scalar metrics plus the
episode-completion arrays. With more than one device the whole program is
pmapped: env states and rollouts shard over the device axis, gradients
pmean across it (the update program's `axis_name`), params stay replicated.

This is the plane for envs with a functional `JaxEnv` form
(`podracer.jax_env`); Python/numpy envs belong on Sebulba.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .jax_env import JaxEnv, autoreset_step, init_env_state, make_jax_env

AXIS = "devices"


def make_anakin_step(env: JaxEnv, module, update_fn, rollout_len: int):
    """Build the fused step: (state, env_state, rng) ->
    (state, env_state, metrics, episode_outs). Pure — jit or pmap it."""

    def anakin_step(state, env_state, rng):
        params, _ = state
        k_roll, k_up = jax.random.split(rng)

        def one_step(est, key):
            obs = env.observe_fn(est["core"])
            k_act, k_reset = jax.random.split(key)
            dist, value = module.forward(params, obs)
            action = module.sample(k_act, dist)
            logp = module.log_prob(dist, action)
            est, out = autoreset_step(env, est, action, k_reset)
            rec = {
                "obs": obs,
                "actions": action,
                "logp": logp,
                "values": value,
                "rewards": out["reward"],
                "dones": out["done"],
                "ep_ret": out["ep_ret"],
                "ep_len": out["ep_len"],
            }
            return est, rec

        env_state, traj = lax.scan(
            one_step, env_state, jax.random.split(k_roll, rollout_len)
        )
        batch = {
            k: traj[k]
            for k in ("obs", "actions", "logp", "values", "rewards", "dones")
        }
        # Bootstrap view: the post-rollout observation (reset obs where an
        # episode just ended — GAE masks it through `dones`, exactly the
        # EnvRunner contract).
        batch["last_obs"] = env.observe_fn(env_state["core"])
        state, metrics = update_fn(state, batch, k_up)
        episodes = {
            "done": traj["dones"],
            "ep_ret": traj["ep_ret"],
            "ep_len": traj["ep_len"],
        }
        return state, env_state, metrics, episodes

    return anakin_step


class AnakinDriver:
    """The Anakin execution plane behind `Algorithm` (PPO first).

    Owns (params, opt_state) — there is no separate LearnerGroup; the
    learner IS the fused program. `training_step()` matches the
    `Algorithm.training_step` contract so `Algorithm.train()` drives either
    plane identically.
    """

    plane = "anakin"

    def __init__(self, algo):
        cfg = algo.config
        self.algo = algo
        self.module = algo.module
        self.env = make_jax_env(cfg.env, **cfg.env_config)
        self.num_devices = D = max(1, int(cfg.podracer_num_devices))
        self.num_envs = B = int(cfg.podracer_num_envs)
        self.rollout_len = T = int(cfg.derived_podracer_rollout_len())
        if D > 1:
            avail = len(jax.devices())
            if D > avail:
                raise ValueError(
                    f"podracer_num_devices={D} > available devices {avail}"
                )
            if B % D != 0:
                raise ValueError(
                    f"podracer_num_envs={B} must divide over "
                    f"podracer_num_devices={D}"
                )
        opt, update_fn = algo._podracer_update_factory(
            axis_name=AXIS if D > 1 else None
        )
        self._opt = opt
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, k_init, k_env = jax.random.split(self._rng, 3)
        params = self.module.init(k_init)
        opt_state = opt.init(params)
        step_fn = make_anakin_step(self.env, self.module, update_fn, T)

        if D > 1:
            devices = jax.devices()[:D]
            self._step = jax.pmap(
                step_fn, axis_name=AXIS, devices=devices,
                donate_argnums=(0, 1),
            )
            env = self.env
            per_dev = B // D
            self._env_state = jax.pmap(
                lambda k: init_env_state(env, k, per_dev), devices=devices
            )(jax.random.split(k_env, D))
            self._state = jax.device_put_replicated((params, opt_state), devices)
        else:
            self._step = jax.jit(step_fn, donate_argnums=(0, 1))
            self._env_state = init_env_state(self.env, k_env, B)
            self._state = (params, opt_state)

    # ----------------------------------------------------------- training
    def _iter_keys(self):
        self._rng, key = jax.random.split(self._rng)
        if self.num_devices > 1:
            return jax.random.split(key, self.num_devices)
        return key

    def training_step(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        self._state, self._env_state, metrics, episodes = self._step(
            self._state, self._env_state, self._iter_keys()
        )
        metrics = jax.tree.map(np.asarray, jax.device_get(metrics))
        dt = time.perf_counter() - t0

        done = np.asarray(episodes["done"]) > 0
        if done.any():
            rets = np.asarray(episodes["ep_ret"])[done]
            lens = np.asarray(episodes["ep_len"])[done]
            self.algo._episode_returns.extend(rets.tolist())
            self.algo._episode_lengths.extend(lens.tolist())
            self.algo._episodes_this_iter += int(done.sum())

        steps = self.rollout_len * self.num_envs
        scalars = {
            k: float(np.asarray(v).reshape(-1)[0]) for k, v in metrics.items()
        }
        _observe_metrics(self.plane, steps, dt)
        return {
            "_env_steps_this_iter": steps,
            "info": {"learner": scalars, "fused_step_seconds": dt},
        }

    # ------------------------------------------------------------ weights
    def get_weights(self):
        params = self._state[0]
        if self.num_devices > 1:
            return jax.tree.map(lambda x: np.asarray(x[0]), params)
        return jax.device_get(params)

    # ----------------------------------------------------------- persist
    def save_state(self) -> bytes:
        params, opt_state = self._state
        if self.num_devices > 1:
            params = jax.tree.map(lambda x: np.asarray(x[0]), params)
            opt_state = jax.tree.map(lambda x: np.asarray(x[0]), opt_state)
        return pickle.dumps((
            jax.device_get(params), jax.device_get(opt_state),
            np.asarray(self._rng),
        ))

    def load_state(self, blob: bytes):
        params, opt_state, rng = pickle.loads(blob)
        self._rng = jnp.asarray(rng)
        if self.num_devices > 1:
            devices = jax.devices()[: self.num_devices]
            self._state = jax.device_put_replicated((params, opt_state), devices)
        else:
            self._state = (params, opt_state)

    def stop(self):
        pass


def _observe_metrics(plane: str, env_steps: int, step_seconds: float):
    """Feed the shared rllib families; never load-bearing (dropped when no
    cluster backend is attached — same rule as every other metric)."""
    try:
        from ...util.metrics import rllib_metrics

        m = rllib_metrics()
        m["rllib_env_steps_total"].inc(env_steps, tags={"plane": plane})
        m["rllib_learner_step_seconds"].observe(
            step_seconds, tags={"plane": plane}
        )
    except Exception:  # noqa: BLE001 — metrics never load-bearing
        pass
