"""Functional (pure-jnp) environments — the Anakin plane's env protocol.

Podracer (arxiv 2104.06272) Anakin fuses environment dynamics into the
learner's jit program: env.step must therefore be a *pure function* on jnp
arrays, so `jax.lax.scan` can unroll rollout collection inside one XLA
program. The protocol here is batched-native (state pytrees carry a leading
[N] env axis) because the classic-control dynamics in `..env.cartpole` /
`..env.pendulum` are already written batched over an array namespace — the
jitted plane calls the SAME functions with `xp=jax.numpy` that the numpy
`VectorEnv`s call with `xp=numpy`, so dynamics parity holds by
construction and `tests/test_podracer_env_parity.py` only has to guard the
wrapper semantics (reset distribution, auto-reset, step accounting).

Protocol (`JaxEnv`):

    reset_fn(key, n)      -> core state pytree with leading [n]
    observe_fn(state)     -> [n, obs_dim] float32
    step_fn(state, action)-> (new_state, reward [n], terminated [n] bool)

Episode bookkeeping (step counters, returns, truncation, auto-reset) is NOT
the env's job — `autoreset_step` wraps any JaxEnv with the exact semantics
the numpy `VectorEnv`s implement: step counters increment before the done
check, truncation fires at max_episode_steps on non-terminated envs,
finished envs are reset in place (the returned observation of a finished
env is its RESET observation), and the pre-reset episode return/length are
exposed so the driver can report completed episodes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..env import cartpole as np_cartpole
from ..env import pendulum as np_pendulum
from ..env.spaces import Box, Discrete


class JaxEnv:
    """Base protocol: subclasses provide pure batched reset/observe/step.

    `observation_space`/`action_space` mirror the numpy VectorEnv surface so
    `Algorithm._make_module` sizes the policy identically for both planes.
    """

    max_episode_steps: int = 1000
    observation_space: Any = None
    action_space: Any = None

    def reset_fn(self, key, n: int):
        raise NotImplementedError

    def observe_fn(self, state):
        raise NotImplementedError

    def step_fn(self, state, action):
        raise NotImplementedError


class JaxCartPole(JaxEnv):
    """CartPole-v1 on jnp — dynamics shared with `env.cartpole`."""

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = max_episode_steps
        self.observation_space = Box(-jnp.inf, jnp.inf, (4,))
        self.action_space = Discrete(2)

    def reset_fn(self, key, n: int):
        return jax.random.uniform(
            key, (n, 4),
            minval=-np_cartpole.RESET_BOUND, maxval=np_cartpole.RESET_BOUND,
            dtype=jnp.float32,
        )

    def observe_fn(self, state):
        return state.astype(jnp.float32)

    def step_fn(self, state, action):
        new_state = np_cartpole.cartpole_step(jnp, state, action)
        reward = jnp.ones(state.shape[0], jnp.float32)
        terminated = np_cartpole.cartpole_terminated(jnp, new_state)
        return new_state, reward, terminated


class JaxPendulum(JaxEnv):
    """Pendulum-v1 on jnp — dynamics shared with `env.pendulum`.

    Core state is [n, 2] (theta, theta_dot); never terminates, truncation
    only.
    """

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = max_episode_steps
        self.observation_space = Box(-jnp.inf, jnp.inf, (3,))
        self.action_space = Box(
            -np_pendulum.MAX_TORQUE, np_pendulum.MAX_TORQUE, (1,)
        )

    def reset_fn(self, key, n: int):
        k_th, k_dot = jax.random.split(key)
        theta = jax.random.uniform(
            k_th, (n,),
            minval=-np_pendulum.RESET_THETA_BOUND,
            maxval=np_pendulum.RESET_THETA_BOUND, dtype=jnp.float32,
        )
        theta_dot = jax.random.uniform(
            k_dot, (n,),
            minval=-np_pendulum.RESET_THETADOT_BOUND,
            maxval=np_pendulum.RESET_THETADOT_BOUND, dtype=jnp.float32,
        )
        return jnp.stack([theta, theta_dot], axis=1)

    def observe_fn(self, state):
        return np_pendulum.pendulum_obs(
            jnp, state[:, 0], state[:, 1]
        ).astype(jnp.float32)

    def step_fn(self, state, action):
        u = jnp.asarray(action, jnp.float32).reshape(state.shape[0])
        theta, theta_dot, cost = np_pendulum.pendulum_step(
            jnp, state[:, 0], state[:, 1], u
        )
        new_state = jnp.stack([theta, theta_dot], axis=1)
        terminated = jnp.zeros(state.shape[0], bool)
        return new_state, (-cost).astype(jnp.float32), terminated


# --------------------------------------------------------------------------
# Auto-reset wrapper state: exactly the VectorEnv bookkeeping, as a pytree.
# --------------------------------------------------------------------------
def init_env_state(env: JaxEnv, key, n: int) -> Dict[str, Any]:
    """Fresh wrapper state: core env state + per-env step/return counters."""
    return {
        "core": env.reset_fn(key, n),
        "steps": jnp.zeros(n, jnp.int32),
        "ep_ret": jnp.zeros(n, jnp.float32),
    }


def autoreset_step(env: JaxEnv, est: Dict[str, Any], action, key):
    """One wrapped step with VectorEnv-parity auto-reset semantics.

    Returns (new_est, out) where `out` carries everything a rollout records:
      reward, terminated, truncated, done (float32 — the GAE mask),
      ep_ret / ep_len (the PRE-reset totals; only meaningful where done).
    Finished envs are already reset inside `new_est` — observing it yields
    the reset observation, matching the numpy env's step return.
    """
    n = est["steps"].shape[0]
    core, reward, terminated = env.step_fn(est["core"], action)
    steps = est["steps"] + 1
    truncated = (~terminated) & (steps >= env.max_episode_steps)
    done = terminated | truncated
    ep_ret = est["ep_ret"] + reward

    fresh = env.reset_fn(key, n)
    # Core may be any pytree with leading [n] leaves; blend per leaf.
    new_core = jax.tree.map(
        lambda f, c: jnp.where(done.reshape((n,) + (1,) * (c.ndim - 1)), f, c),
        fresh, core,
    )
    new_est = {
        "core": new_core,
        "steps": jnp.where(done, 0, steps),
        "ep_ret": jnp.where(done, 0.0, ep_ret),
    }
    out = {
        "reward": reward,
        "terminated": terminated,
        "truncated": truncated,
        "done": done.astype(jnp.float32),
        "ep_ret": ep_ret,
        "ep_len": steps,
    }
    return new_est, out


# --------------------------------------------------------------------------
# Registry (parallel to ..env's numpy registry; same names resolve to the
# functional forms so one AlgorithmConfig.environment() drives either plane)
# --------------------------------------------------------------------------
_JAX_ENV_REGISTRY: Dict[str, Callable[..., JaxEnv]] = {}


def register_jax_env(name: str, ctor: Callable[..., JaxEnv]) -> None:
    _JAX_ENV_REGISTRY[name] = ctor


def make_jax_env(name: str, **kwargs) -> JaxEnv:
    if name not in _JAX_ENV_REGISTRY:
        raise KeyError(
            f"No functional (JaxEnv) form registered for {name!r} — the "
            f"Anakin plane needs pure-jnp dynamics. Registered: "
            f"{sorted(_JAX_ENV_REGISTRY)}. Python-loop envs belong on the "
            "Sebulba plane (config.podracer('sebulba'))."
        )
    return _JAX_ENV_REGISTRY[name](**kwargs)


def jax_env_registered(name: str) -> bool:
    return name in _JAX_ENV_REGISTRY


register_jax_env("CartPole-v1", JaxCartPole)
register_jax_env("Pendulum-v1", JaxPendulum)
