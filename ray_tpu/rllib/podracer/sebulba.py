"""Sebulba — actor/learner split over the gang + block-transport planes
(Podracer §3, arxiv 2104.06272).

Topology: N actor-gang members (each wrapping the existing numpy
`EnvRunner` — this is the plane for Python-loop envs) + 1 learner member
hosting the algorithm's jitted update program. Three data planes:

  * trajectories: each actor lands its time-major fragment as ONE arena
    object (`podracer.transport.TrajTransport` — pickle-5 frame,
    `put_serialized` span descriptors) and returns only the descriptor;
    the learner imports same-node off the arena mapping or cross-node as
    one bulk span pull;
  * parameters: the learner broadcasts weights over ONE compiled-DAG edge
    channel (`make_edge_channel`: shm seqlock same-node, TCP cross-node)
    with a reader slot per actor — depth-1 backpressure means a broadcast
    returns only after every actor acked the previous one;
  * control: plain actor RPCs, sliced short so the driver consults the
    GangSupervisor between waits.

Elasticity (the PR 4 machinery): the supervisor watches all N+1 members
through the controller death feed; any member death aborts the whole gang
within the failure deadline, then restart policy + backoff + RESHAPE — the
actor count is re-picked from currently-feasible capacity within
[min_actors, num_actors], the learner restores from the driver-cached state
blob, and the global step counter continues where it left off.
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...dag.compiled import ChannelHostMixin

logger = logging.getLogger(__name__)


class SebulbaGangError(RuntimeError):
    pass


class _ActorMember(ChannelHostMixin):
    """Gang actor: one EnvRunner + the trajectory publish side."""

    def __init__(self, payload: bytes):
        import cloudpickle

        o = cloudpickle.loads(payload)
        from ..env.env_runner import EnvRunner
        from .transport import TrajTransport

        self._runner = EnvRunner(
            env_name=o["env_name"],
            num_envs=o["num_envs"],
            module=o["module"],
            rollout_len=o["rollout_len"],
            seed=o["seed"],
            env_kwargs=o["env_kwargs"],
        )
        self._transport = TrajTransport(
            inline_max_bytes=o["inline_max_bytes"],
            timeout_s=o["channel_timeout_s"],
        )
        self._timeout_s = o["channel_timeout_s"]
        self._param_reader = None
        self._params = None

    def ping(self) -> str:
        return "ok"

    def pid(self) -> int:
        import os

        return os.getpid()

    def bind_param_channel(self, reader) -> str:
        self._param_reader = reader
        return "ok"

    def collect(self, sync: bool) -> Dict[str, Any]:
        """One fragment: (optionally) receive fresh params off the broadcast
        channel, roll the envs, publish the batch, return the descriptor."""
        if sync:
            self._params = self._param_reader.begin_read(
                timeout=self._timeout_s
            )
            self._param_reader.end_read()
        if self._params is None:
            raise RuntimeError(
                "collect(sync=False) before any parameter broadcast"
            )
        batch = self._runner.sample(self._params)
        episode_returns = batch.pop("episode_returns")
        episode_lengths = batch.pop("episode_lengths")
        from ...util import flight

        t0 = flight.now_ns()
        desc = self._transport.publish(batch)
        flight.record("sebulba.publish", t0, flight.now_ns(),
                      lane="rl/actor", attrs={"frames": len(batch)})
        # Cluster-clock publish stamp: the learner turns the gap between
        # this and its fetch into an actor->learner queue-wait span.
        desc = dict(desc)
        desc["published_at"] = flight.cluster_time()
        return {
            "desc": desc,
            "episode_returns": episode_returns,
            "episode_lengths": episode_lengths,
            "transport": dict(self._transport.stats),
        }


class _LearnerMember(ChannelHostMixin):
    """Gang actor hosting the jitted update program + the broadcast side."""

    def __init__(self, payload: bytes):
        import cloudpickle
        import jax

        o = cloudpickle.loads(payload)
        from .transport import TrajTransport

        self._module = o["module"]
        self._opt = o["opt"]
        self._update = jax.jit(o["update_fn"], donate_argnums=(0,))
        self._rng = jax.random.PRNGKey(o["seed"])
        if o.get("state_blob") is not None:
            params, opt_state, rng = pickle.loads(o["state_blob"])
            self._rng = jax.numpy.asarray(rng)
        else:
            params = o["init_params"]
            opt_state = self._opt.init(params)
        self._state = (params, opt_state)
        self._transport = TrajTransport(timeout_s=o["channel_timeout_s"])
        self._chan = None

    def ping(self) -> str:
        return "ok"

    def bind_param_channel(self, chan) -> str:
        self._chan = chan
        return "ok"

    def broadcast(self, timeout_s: float = 60.0) -> str:
        """Write current weights to every actor's reader slot. Returns after
        the channel accepted the write — which, at depth 1, also proves
        every actor acked the PREVIOUS broadcast."""
        self._chan.write(self.get_weights(), timeout=timeout_s)
        return "ok"

    def update(self, descs: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Import every actor's fragment (arena/bulk rungs), concat along
        the env axis, run the update program."""
        import jax

        from ...util import flight

        self._gauge_queue_depth(len(descs))
        # Queue-wait spans: published_at is the actor's cluster-clock stamp
        # (both ends clock-aligned at registration), so the span length IS
        # the time the fragment sat between the gangs — the latency the
        # rllib_actor_learner_queue_depth gauge only counts.
        fetch_t = flight.cluster_time()
        batches = []
        for d in descs:
            d = dict(d)
            pub = d.pop("published_at", None)
            if pub is not None and flight.enabled():
                wait = max(fetch_t - pub, 0.0)
                t1 = flight.now_ns()
                flight.record("sebulba.queue_wait",
                              t1 - int(wait * 1e9), t1,
                              lane="rl/learner", attrs={"depth": len(descs)})
            t0 = flight.now_ns()
            batches.append(self._transport.fetch(d))
            flight.record("sebulba.import", t0, flight.now_ns(),
                          lane="rl/learner")
        if len(batches) == 1:
            batch = batches[0]
        else:
            batch = {
                k: np.concatenate(
                    [b[k] for b in batches],
                    axis=0 if k == "last_obs" else 1,
                )
                for k in batches[0]
            }
        self._gauge_queue_depth(0)
        self._rng, key = jax.random.split(self._rng)
        t0 = time.perf_counter()
        self._state, metrics = self._update(self._state, batch, key)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        dt = time.perf_counter() - t0
        T, B = batches[0]["rewards"].shape[0], sum(
            b["rewards"].shape[1] for b in batches
        )
        self._observe(T * B, dt)
        return {
            "metrics": metrics,
            "env_steps": T * B,
            "learner_step_seconds": dt,
            "state": self.save_state(),
            "transport": dict(self._transport.stats),
        }

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, jax.device_get(self._state[0]))

    def save_state(self) -> bytes:
        import jax

        params, opt_state = jax.device_get(self._state)
        return pickle.dumps(
            (params, opt_state, np.asarray(self._rng))
        )

    def _gauge_queue_depth(self, depth: int):
        try:
            from ...util.metrics import rllib_metrics

            rllib_metrics()["rllib_actor_learner_queue_depth"].set(
                depth, tags={"plane": "sebulba"}
            )
        except Exception:  # noqa: BLE001 — metrics never load-bearing
            pass

    def _observe(self, env_steps: int, dt: float):
        try:
            from .anakin import _observe_metrics

            _observe_metrics("sebulba", env_steps, dt)
        except Exception:  # noqa: BLE001
            pass


class _SebulbaGang:
    """Supervisor-facing shim: N actor members + the learner + the channel."""

    def __init__(self, actors, learner, channel):
        self.actors = actors
        self.learner = learner
        self.channel = channel

    def actor_ids(self) -> List[str]:
        return [a._id.hex() for a in self.actors + [self.learner]]

    def shutdown(self):
        from ...core import api

        for a in self.actors + [self.learner]:
            try:
                api.kill(a)
            except Exception:  # noqa: BLE001
                pass
        if self.channel is not None:
            try:
                self.channel.destroy()
            except Exception:  # noqa: BLE001
                pass


class SebulbaDriver:
    """The Sebulba execution plane behind `Algorithm`."""

    plane = "sebulba"

    def __init__(self, algo):
        import ray_tpu

        cfg = algo.config
        self.algo = algo
        self.cfg = cfg
        self.num_actors = int(cfg.podracer_num_actors)
        if not ray_tpu.is_initialized():
            # Actors + learner each ask for one CPU; an auto-booted local
            # cluster defaults to CPU=1 and would never place the gang.
            ray_tpu.init(
                ignore_reinit_error=True, num_cpus=self.num_actors + 2
            )
        self._ray = ray_tpu
        self.rollout_len = int(cfg.derived_podracer_rollout_len())
        self._broadcast_interval = max(1, int(cfg.podracer_broadcast_interval))
        self._step_timeout_s = 120.0
        self._iters_since_spawn = 0
        self._state_blob: Optional[bytes] = None  # reshape restore point
        self._weights = None
        self.gang: Optional[_SebulbaGang] = None
        self.transport_stats: Dict[str, Dict[str, int]] = {}

        from ...train.config import FailureConfig, ScalingConfig
        from ...train.elastic import GangSupervisor

        self._supervisor = GangSupervisor(
            ScalingConfig(
                num_workers=self.num_actors + 1,
                min_workers=int(cfg.podracer_min_actors) + 1,
                max_workers=self.num_actors + 1,
                resources_per_worker={"CPU": 1},
            ),
            FailureConfig(max_failures=int(cfg.podracer_max_restarts)),
            experiment_name=f"sebulba-{cfg.env}",
        )
        self._spawn(self.num_actors)

    # -------------------------------------------------------------- spawn
    def _spawn(self, n_actors: int):
        import cloudpickle

        from ...core import api
        from ...core.runtime_context import get_runtime_context
        from ...dag.compiled import make_edge_channel

        cfg = self.cfg
        algo = self.algo
        opt, update_fn = algo._podracer_update_factory(axis_name=None)
        init_params = (
            self._weights if self._weights is not None
            else algo.module.init(
                __import__("jax").random.PRNGKey(cfg.seed)
            )
        )
        learner_payload = cloudpickle.dumps(dict(
            module=algo.module, opt=opt, update_fn=update_fn,
            seed=cfg.seed, init_params=init_params,
            state_blob=self._state_blob, channel_timeout_s=60.0,
        ))
        RemoteLearner = api.remote(_LearnerMember)
        learner = RemoteLearner.options(num_cpus=1).remote(learner_payload)

        RemoteActor = api.remote(_ActorMember)
        actors = []
        for i in range(n_actors):
            payload = cloudpickle.dumps(dict(
                env_name=cfg.env, env_kwargs=cfg.env_config,
                num_envs=cfg.podracer_envs_per_actor,
                module=algo.module, rollout_len=self.rollout_len,
                seed=cfg.seed + 1 + i,
                inline_max_bytes=64 * 1024, channel_timeout_s=60.0,
            ))
            actors.append(RemoteActor.options(num_cpus=1).remote(payload))

        try:
            # ONE broadcast channel: producer = learner, a reader slot per
            # actor (shm when colocated, TCP across nodes).
            driver_node = get_runtime_context().get_node_id()
            nodes = api.get(
                [a.node_id.remote() for a in [learner] + actors],
                timeout=self._step_timeout_s,
            )
            channel = make_edge_channel(
                1 << 20, nodes[0], nodes[1:], n_actors, learner, driver_node
            )
            binds = [learner.bind_param_channel.remote(channel)]
            binds += [
                a.bind_param_channel.remote(channel.with_reader_slot(i))
                for i, a in enumerate(actors)
            ]
            api.get(binds, timeout=self._step_timeout_s)
        except Exception as e:  # noqa: BLE001 — a member died mid-setup
            gang = _SebulbaGang(actors, learner, None)
            gang.shutdown()
            raise SebulbaGangError(f"gang setup failed: {e!r}") from e

        self.gang = _SebulbaGang(actors, learner, channel)
        self.num_actors = n_actors
        self._iters_since_spawn = 0
        self._supervisor.watch(self.gang)
        if self._weights is None:
            self._weights = api.get(
                learner.get_weights.remote(), timeout=self._step_timeout_s
            )

    # ----------------------------------------------------------- training
    def training_step(self) -> Dict[str, Any]:
        """One iteration, elastically: on a gang failure mid-iteration the
        gang is aborted, reshaped, respawned from the last learner state,
        and the iteration RETRIED — one train() call survives member death
        (the chaos test kills an actor here)."""
        recovery_t0 = None
        while True:
            try:
                result = self._one_iteration()
                if recovery_t0 is not None:
                    self._supervisor.record_recovery(
                        time.monotonic() - recovery_t0
                    )
                return result
            except SebulbaGangError as e:
                if recovery_t0 is None:
                    recovery_t0 = time.monotonic()
                self._supervisor.abort_mesh(self.gang)
                self.gang = None
                decision = self._supervisor.on_failure(str(e))
                if decision.stop:
                    raise RuntimeError(
                        f"sebulba gang failed permanently after "
                        f"{self._supervisor.attempts} attempt(s): {e}"
                    ) from e
                logger.warning(
                    "sebulba gang failure (%s) — restart %d after %.1fs",
                    e, self._supervisor.attempts, decision.backoff_s,
                )
                if decision.backoff_s > 0:
                    time.sleep(decision.backoff_s)
                world = self._supervisor.plan_world_size()
                lo = int(self.cfg.podracer_min_actors)
                hi = int(self.cfg.podracer_num_actors)
                n = max(lo, min(hi, (world or hi + 1) - 1))
                if n != self.num_actors:
                    logger.warning(
                        "sebulba reshapes: %d -> %d actors",
                        self.num_actors, n,
                    )
                self._spawn(n)

    def _one_iteration(self) -> Dict[str, Any]:
        from ...core import api

        self._check_failure()
        gang = self.gang
        # Always sync on the first iteration after a (re)spawn — fresh
        # actors have no weights until a broadcast lands.
        sync = (
            self._iters_since_spawn == 0
            or self._iters_since_spawn % self._broadcast_interval == 0
        )
        if sync:
            bref = gang.learner.broadcast.remote(self._step_timeout_s)
        crefs = [a.collect.remote(sync) for a in gang.actors]
        if sync:
            self._get([bref])
        outs = self._get(crefs)
        for o in outs:
            rets = list(o["episode_returns"])
            self.algo._episode_returns.extend(rets)
            self.algo._episode_lengths.extend(list(o["episode_lengths"]))
            self.algo._episodes_this_iter += len(rets)
        self.transport_stats["actors"] = [o["transport"] for o in outs]

        descs = [o["desc"] for o in outs]
        (up,) = self._get([gang.learner.update.remote(descs)])
        self._weights = None  # invalidated; refetched lazily
        self._state_blob = up["state"]
        self.transport_stats["learner"] = up["transport"]
        self._iters_since_spawn += 1
        return {
            "_env_steps_this_iter": up["env_steps"],
            "info": {
                "learner": up["metrics"],
                "learner_step_seconds": up["learner_step_seconds"],
                "num_actors": self.num_actors,
            },
        }

    def _check_failure(self):
        reason = self._supervisor.failure()
        if reason:
            raise SebulbaGangError(f"gang member died ({reason})")

    def _get(self, refs):
        """api.get in SHORT slices, consulting the supervisor between them
        (the MPMD trainer's pattern): a death detected through the
        controller feed aborts within the poll window instead of waiting
        out a full RPC deadline on members that will never answer."""
        from ...core import api
        from ...core.exceptions import GetTimeoutError

        deadline = time.monotonic() + self._step_timeout_s
        while True:
            self._check_failure()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise SebulbaGangError(
                    f"step timed out after {self._step_timeout_s:.0f}s"
                )
            try:
                return api.get(refs, timeout=min(2.0, remaining))
            except GetTimeoutError:
                continue
            except Exception as e:  # noqa: BLE001 — a member died
                raise SebulbaGangError(f"step failed: {e!r}") from e

    # ------------------------------------------------------------ weights
    def get_weights(self):
        if self._weights is None:
            if self._state_blob is not None:
                self._weights = pickle.loads(self._state_blob)[0]
            else:
                self._weights = self._get(
                    [self.gang.learner.get_weights.remote()]
                )[0]
        return self._weights

    # ----------------------------------------------------------- persist
    def save_state(self) -> bytes:
        if self._state_blob is None:
            (self._state_blob,) = self._get(
                [self.gang.learner.save_state.remote()]
            )
        return self._state_blob

    def load_state(self, blob: bytes):
        self._state_blob = blob
        self._weights = pickle.loads(blob)[0]
        # Restore by respawning the learner side from the blob — the same
        # path a reshape takes, so it is exercised constantly.
        if self.gang is not None:
            self._supervisor.stop_watch()
            self.gang.shutdown()
        self._spawn(self.num_actors)

    def stop(self):
        self._supervisor.stop_watch()
        if self.gang is not None:
            self.gang.shutdown()
            self.gang = None
