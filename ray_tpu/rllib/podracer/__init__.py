"""Podracer execution planes (arxiv 2104.06272) behind one config surface.

`AlgorithmConfig.podracer("anakin")` fuses batched env dynamics into the
learner's jit program (`anakin.AnakinDriver` + the pure-jnp envs in
`jax_env`); `AlgorithmConfig.podracer("sebulba")` splits a numpy-env actor
gang from the learner, trajectories riding the block transport and params
returning over compiled-DAG channels (`sebulba.SebulbaDriver`).
"""

from .jax_env import (
    JaxCartPole,
    JaxEnv,
    JaxPendulum,
    autoreset_step,
    init_env_state,
    jax_env_registered,
    make_jax_env,
    register_jax_env,
)

__all__ = [
    "JaxCartPole",
    "JaxEnv",
    "JaxPendulum",
    "AnakinDriver",
    "SebulbaDriver",
    "autoreset_step",
    "init_env_state",
    "jax_env_registered",
    "make_jax_env",
    "register_jax_env",
]


def __getattr__(name):  # lazy: importing jax_env must not pull in transport
    if name == "AnakinDriver":
        from .anakin import AnakinDriver

        return AnakinDriver
    if name == "SebulbaDriver":
        from .sebulba import SebulbaDriver

        return SebulbaDriver
    raise AttributeError(name)
