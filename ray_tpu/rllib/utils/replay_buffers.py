"""Replay buffer framework (reference: `rllib/utils/replay_buffers/` —
`ReplayBuffer`, `PrioritizedReplayBuffer`, `MultiAgentReplayBuffer`).

TPU-first shape: buffers live host-side in flat numpy rings and SAMPLE in
stacked [k, mb, ...] layouts so the learner consumes k minibatches in one
jit-compiled `lax.scan` — one device transfer per training iteration, not
per gradient step.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform circular transition buffer for off-policy algorithms.

    Actions may be discrete (scalar int) or continuous ([act_dim] float).
    `add_fragment` flattens the EnvRunner's time-major [T, B] rollout
    fragments into transitions (computing next_obs from the fragment).
    """

    def __init__(self, capacity: int, obs_dim: int, act_shape: Tuple[int, ...] = (),
                 act_dtype=np.int32):
        self.capacity = capacity
        self.obs = np.empty((capacity, obs_dim), np.float32)
        self.next_obs = np.empty((capacity, obs_dim), np.float32)
        self.actions = np.empty((capacity, *act_shape), act_dtype)
        self.rewards = np.empty(capacity, np.float32)
        self.dones = np.empty(capacity, np.float32)
        self.size = 0
        self.pos = 0

    def __len__(self) -> int:
        return self.size

    def add_fragment(self, batch: Dict[str, np.ndarray]):
        obs, dones = batch["obs"], batch["dones"]
        T, B = dones.shape
        next_obs = np.concatenate([obs[1:], batch["last_obs"][None]], axis=0)
        n = T * B
        self._put(
            idx=(self.pos + np.arange(n)) % self.capacity,
            obs=obs.reshape(n, -1),
            next_obs=next_obs.reshape(n, -1),
            actions=batch["actions"].reshape((n, *self.actions.shape[1:])),
            rewards=batch["rewards"].reshape(n),
            dones=dones.reshape(n),
        )
        self.pos = (self.pos + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def _put(self, idx, obs, next_obs, actions, rewards, dones):
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.dones[idx] = dones

    def _gather(self, idx) -> Dict[str, np.ndarray]:
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }

    def sample(self, rng: np.random.Generator, k: int, mb: int) -> Dict[str, np.ndarray]:
        """k uniform minibatches of size mb, stacked [k, mb, ...]."""
        return self._gather(rng.integers(0, self.size, size=(k, mb)))


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    `rllib/utils/replay_buffers/prioritized_replay_buffer.py`; Schaul et al.).

    Keeps per-transition priorities p_i; samples ∝ p_i^alpha with
    importance-sampling weights (β-annealed by the caller). Priorities for
    sampled transitions are updated from TD errors via `update_priorities`.
    """

    def __init__(self, capacity: int, obs_dim: int, act_shape: Tuple[int, ...] = (),
                 act_dtype=np.int32, alpha: float = 0.6):
        super().__init__(capacity, obs_dim, act_shape, act_dtype)
        self.alpha = alpha
        self.priorities = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add_fragment(self, batch: Dict[str, np.ndarray]):
        T, B = batch["dones"].shape
        n = T * B
        idx = (self.pos + np.arange(n)) % self.capacity
        super().add_fragment(batch)
        self.priorities[idx] = self._max_prio  # new data gets max priority

    def sample(
        self, rng: np.random.Generator, k: int, mb: int, beta: float = 0.4
    ) -> Dict[str, np.ndarray]:
        p = self.priorities[: self.size] ** self.alpha
        probs = p / p.sum()
        idx = rng.choice(self.size, size=(k, mb), p=probs)
        out = self._gather(idx)
        weights = (self.size * probs[idx]) ** (-beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["indices"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indices: np.ndarray, td_errors: np.ndarray):
        prios = np.abs(np.asarray(td_errors, np.float64)).reshape(-1) + 1e-6
        self.priorities[np.asarray(indices).reshape(-1)] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
