"""Generalized advantage estimation — shared by the on-policy learners
(PPO, A2C; reference analog: `rllib/evaluation/postprocessing.py`
compute_gae_for_sample_batch, as one in-jit scan)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def compute_gae(module, params, batch, gamma: float, lam: float):
    """Time-major batch → (advantages, returns), both [T, B]."""
    rewards, dones, values = batch["rewards"], batch["dones"], batch["values"]
    _, last_val = module.forward(params, batch["last_obs"])

    def gae_step(carry, x):
        adv_next, v_next = carry
        r, d, v = x
        delta = r + gamma * v_next * (1.0 - d) - v
        adv = delta + gamma * lam * (1.0 - d) * adv_next
        return (adv, v), adv

    B = rewards.shape[1]
    (_, _), advs = lax.scan(
        gae_step,
        (jnp.zeros(B, values.dtype), last_val),
        (rewards, dones, values),
        reverse=True,
    )
    return advs, advs + values


def flatten_time_major(batch, advs, returns):
    """[T, B, ...] rollouts → flat per-sample dict for minibatching."""
    T, B = batch["rewards"].shape
    N = T * B
    return {
        "obs": batch["obs"].reshape(N, -1),
        "actions": batch["actions"].reshape((N,) + batch["actions"].shape[2:]),
        "logp": batch["logp"].reshape(N),
        "values": batch["values"].reshape(N),
        "adv": advs.reshape(N),
        "returns": returns.reshape(N),
    }
