from . import replay_buffers

__all__ = ["replay_buffers"]
