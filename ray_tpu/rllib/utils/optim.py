"""Shared optimizer construction for algorithm learners."""

from __future__ import annotations

import optax


def make_optimizer(cfg, kind: str = "adam"):
    """grad-clip (when configured) chained onto the base optimizer — the
    block every `_make_learner` needs."""
    chain = []
    if cfg.grad_clip is not None:
        chain.append(optax.clip_by_global_norm(cfg.grad_clip))
    if kind == "adam":
        chain.append(optax.adam(cfg.lr))
    elif kind == "rmsprop":
        chain.append(optax.rmsprop(cfg.lr, decay=0.99, eps=0.1))
    else:
        raise ValueError(f"unknown optimizer kind {kind!r}")
    return optax.chain(*chain)
