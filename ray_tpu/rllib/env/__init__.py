"""Environment API + registry (reference: `rllib/env/`).

The reference delegates to gymnasium; this image has no gym, so classic
control environments are implemented natively — and *vectorized in numpy*
from the start, which is the shape the TPU stack wants anyway (EnvRunner
actors step [N]-env batches, the policy forward is one XLA call per batch).

API is gymnasium-flavored:
    reset(seed) -> (obs, info);  step(a) -> (obs, rew, terminated, truncated, info)
Vector envs auto-reset finished sub-envs and report completed episode
returns/lengths in `info`.

Dynamics live in xp-generic module functions (`cartpole.cartpole_step`,
`pendulum.pendulum_step`, ... parameterized over numpy|jnp) so the numpy
VectorEnvs here and the traceable `podracer.jax_env` forms share ONE
implementation — `tests/test_podracer_env_parity.py` holds them equal.
"""

from __future__ import annotations

from typing import Callable, Dict

from .spaces import Box, Discrete, Space
from .vector import VectorEnv

_ENV_REGISTRY: Dict[str, Callable[..., VectorEnv]] = {}


def register_env(name: str, ctor: Callable[..., VectorEnv]) -> None:
    """Register a vector-env constructor: ctor(num_envs, **kwargs) -> VectorEnv.

    Reference analog: `ray.tune.registry.register_env` (used throughout
    rllib/algorithms) — here envs are registered directly with the RL lib.
    """
    _ENV_REGISTRY[name] = ctor


def make_env(name: str, num_envs: int = 1, **kwargs) -> VectorEnv:
    if name not in _ENV_REGISTRY:
        raise KeyError(
            f"Unknown env {name!r}. Registered: {sorted(_ENV_REGISTRY)}. "
            "Use register_env(name, ctor) for custom environments."
        )
    return _ENV_REGISTRY[name](num_envs, **kwargs)


def _register_builtins():
    from .cartpole import VectorCartPole
    from .pendulum import VectorPendulum

    register_env("CartPole-v1", VectorCartPole)
    register_env("Pendulum-v1", VectorPendulum)

    def _multi_cartpole(num_envs, num_agents: int = 2, **kwargs):
        # Shared-policy multi-agent CartPole: num_envs policy slots total.
        from .multi_agent import SharedPolicyVectorEnv, make_multi_agent

        # Slots come in whole instances (instances × agents). num_envs below
        # one instance (e.g. the space-probe's num_envs=1) rounds up to one.
        if num_envs > num_agents and num_envs % num_agents != 0:
            raise ValueError(
                f"MultiCartPole needs num_envs ({num_envs}) divisible by "
                f"num_agents ({num_agents}) — slots are instances × agents"
            )
        ma_cls = make_multi_agent(VectorCartPole, num_agents=num_agents)
        return SharedPolicyVectorEnv(lambda: ma_cls(**kwargs), max(num_envs // num_agents, 1))

    register_env("MultiCartPole", _multi_cartpole)


_register_builtins()

__all__ = [
    "Box",
    "Discrete",
    "Space",
    "VectorEnv",
    "register_env",
    "make_env",
]
